"""apex_tpu.telemetry.timeline — device-timeline observability
(ISSUE 13).

The acceptance gates:

  * on a synthetic two-lane device trace with known overlap, the
    decomposition recovers exposed-comm ms EXACTLY (interval-
    subtraction oracle: fully-hidden, fully-exposed, and
    partial-overlap collectives);
  * a straggling device z-scores away from the mesh and lands a
    ``timeline.straggler`` event; a uniform mesh stays quiet;
  * ``step.device_compute_ms`` / ``step.exposed_comm_ms`` /
    ``step.device_idle_ms`` gauges ride the Registry's batched flush
    as schema-valid records;
  * ``python -m apex_tpu.telemetry timeline <profiler-dir>`` renders
    the decomposition from a jax-profiler run-dir fixture;
  * the measured ``exposed_comm_fraction`` round-trips
    ``apply_perf_results.decide()`` -> ``tuned_defaults.json`` ->
    ``parallel.plan.predict``'s overlap factor, changing the predicted
    exposed-comm time;
  * a closing SlowStepSentinel capture window feeds the profiler dir
    through the decomposition and attaches the per-step table to a
    flight-dump ``sections`` block.
"""
import gzip
import importlib.util
import json
import os
import subprocess
import sys

import pytest

from apex_tpu.telemetry import (MemorySink, Registry, records_violations,
                                timeline, trace)
from apex_tpu.utils import tuning

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_apply():
    spec = importlib.util.spec_from_file_location(
        "apply_perf_results", os.path.join(ROOT, "tools",
                                           "apply_perf_results.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def dev(name, ts, dur, device=0, args=None):
    """One parsed device event (the pyprof.parse shape)."""
    return {"name": name, "ts": float(ts), "dur": float(dur),
            "pid": device + 10, "tid": 1,
            "process": f"/device:TPU:{device}", "thread": "XLA Op",
            "args": args or {}}


def host(name, ts, dur, step=None):
    args = {} if step is None else {"step": step}
    return {"name": name, "ts": float(ts), "dur": float(dur),
            "pid": 1, "tid": 1, "process": "apex_tpu",
            "thread": "MainThread", "args": args}


# ---------------------------------------------------------------------------
# interval arithmetic oracle
# ---------------------------------------------------------------------------

def test_interval_merge_and_subtract_oracle():
    m = timeline._merge([(10, 20), (15, 30), (40, 50), (50, 60), (5, 6)])
    assert m == [(5, 6), (10, 30), (40, 60)]
    # subtraction: exact complements, adjacent bounds excluded
    assert timeline._subtract([(0, 100)], [(20, 30), (50, 60)]) == \
        [(0, 20), (30, 50), (60, 100)]
    assert timeline._subtract([(10, 20)], [(0, 100)]) == []
    assert timeline._subtract([(10, 20)], []) == [(10, 20)]
    assert timeline._subtract([(10, 20), (30, 40)], [(15, 35)]) == \
        [(10, 15), (35, 40)]


def test_event_op_class_bins_and_async_pairs():
    assert timeline.event_op_class("all-reduce.7") == "collective"
    assert timeline.event_op_class("all-reduce-start.7") == "collective"
    assert timeline.event_op_class("reduce-scatter-done.2") == "collective"
    assert timeline.event_op_class("dot.3") == "blas"
    assert timeline.event_op_class("fusion.12") == "pointwise"
    assert timeline.event_op_class("copy.1") == "memory"
    # non-HLO spans (python frames, runtime noise) classify as None
    assert timeline.event_op_class("$main.py:12 train") is None
    assert timeline.event_op_class("Thread 7") is None


def test_decompose_exposed_comm_oracle():
    """THE acceptance oracle: known overlap recovers exactly.

    device 0: compute [0,100), collective [50,150)  -> exposed 50us
    device 1: compute [0,100), collective [20, 60)  -> fully hidden, 0
    device 2: no compute,      collective [200,260) -> fully exposed 60
    """
    evs = [
        dev("fusion.1", 0, 100, device=0),
        dev("all-reduce.2", 50, 100, device=0),
        dev("fusion.1", 0, 100, device=1),
        dev("all-reduce.2", 20, 40, device=1),
        dev("all-reduce-start.9", 200, 60, device=2),
    ]
    d = timeline.decompose(evs)
    assert d["devices"] == ["/device:TPU:0", "/device:TPU:1",
                            "/device:TPU:2"]
    assert d["n_steps"] == 1                   # one-shot capture window
    rows = d["steps"][0]["devices"]
    assert rows["/device:TPU:0"]["exposed_comm_ms"] == pytest.approx(0.050)
    assert rows["/device:TPU:0"]["comm_ms"] == pytest.approx(0.100)
    assert rows["/device:TPU:0"]["compute_ms"] == pytest.approx(0.100)
    assert rows["/device:TPU:0"]["busy_ms"] == pytest.approx(0.150)
    assert rows["/device:TPU:1"]["exposed_comm_ms"] == 0.0     # hidden
    assert rows["/device:TPU:2"]["exposed_comm_ms"] == \
        pytest.approx(0.060)                                    # exposed
    t = d["totals"]
    assert t["exposed_comm_ms"] == pytest.approx(0.110)
    assert t["comm_ms"] == pytest.approx(0.200)
    assert t["exposed_comm_fraction"] == pytest.approx(0.55)
    # idle = window minus busy, never negative
    window_ms = d["steps"][0]["dur_ms"]
    for r in rows.values():
        assert r["idle_ms"] == pytest.approx(window_ms - r["busy_ms"])


def test_decompose_split_collective_pieces_sum_exactly():
    """A collective split across multiple device events (async chunks)
    still subtracts exactly — interval math, not per-event guesses."""
    evs = [
        dev("fusion.1", 0, 80),
        dev("all-reduce.1", 40, 30),       # [40,70): hidden
        dev("all-reduce.2", 70, 30),       # [70,100): 10 hidden, 20 exposed
    ]
    d = timeline.decompose(evs)
    r = d["steps"][0]["devices"]["/device:TPU:0"]
    assert r["exposed_comm_ms"] == pytest.approx(0.020)
    assert r["comm_ms"] == pytest.approx(0.060)


def test_comm_free_capture_has_null_fraction():
    d = timeline.decompose([dev("fusion.1", 0, 100)])
    assert d["totals"]["comm_ms"] == 0.0
    assert d["totals"]["exposed_comm_fraction"] is None


def test_step_windows_from_host_train_step_spans():
    """Host ``train.step`` spans (a merged timeline) delimit the
    windows; device activity decomposes per step."""
    evs = [
        host("train.step", 0, 100, step=1),
        host("train.step", 100, 100, step=2),
        dev("fusion.1", 10, 50),               # step 1 compute
        dev("all-reduce.1", 120, 40),          # step 2, fully exposed
    ]
    d = timeline.decompose(evs)
    assert [s["step"] for s in d["steps"]] == [1, 2]
    s1, s2 = d["steps"]
    assert s1["devices"]["/device:TPU:0"]["compute_ms"] == \
        pytest.approx(0.050)
    assert s1["devices"]["/device:TPU:0"]["comm_ms"] == 0.0
    assert s2["devices"]["/device:TPU:0"]["exposed_comm_ms"] == \
        pytest.approx(0.040)


def test_cpu_capture_fallback_sniffs_hlo_lanes():
    """A capture whose exporter did not name device processes (CPU
    backend) still decomposes: lanes that are mostly HLO-shaped names
    are treated as device lanes; python threads are not."""
    evs = [
        {"name": "fusion.1", "ts": 0.0, "dur": 100.0, "pid": 1, "tid": 2,
         "process": "/host:CPU", "thread": "XLA Op", "args": {}},
        {"name": "all-reduce.3", "ts": 50.0, "dur": 100.0, "pid": 1,
         "tid": 2, "process": "/host:CPU", "thread": "XLA Op", "args": {}},
        {"name": "$main.py:1 step", "ts": 0.0, "dur": 500.0, "pid": 1,
         "tid": 9, "process": "/host:CPU", "thread": "python", "args": {}},
    ]
    d = timeline.decompose(evs)
    assert len(d["devices"]) == 1
    assert d["totals"]["exposed_comm_ms"] == pytest.approx(0.050)


# ---------------------------------------------------------------------------
# stragglers
# ---------------------------------------------------------------------------

def _mesh_step_events(busy_us_per_dev, step_ts=0.0):
    evs = []
    for i, busy in enumerate(busy_us_per_dev):
        evs.append(dev("fusion.1", step_ts, busy, device=i))
    return evs


def test_straggler_flagged_and_uniform_mesh_quiet():
    # uniform mesh: nothing flags
    d = timeline.decompose(_mesh_step_events([100, 101, 99, 100]))
    assert d["stragglers"] == []
    # one device 2x slower: flagged with a leave-one-out z
    d2 = timeline.decompose(_mesh_step_events([100, 100, 100, 200]))
    assert len(d2["stragglers"]) == 1
    row = d2["stragglers"][0]
    assert row["device"] == "/device:TPU:3"
    assert row["z"] >= timeline.STRAGGLER_Z
    assert row["busy_ms"] == pytest.approx(0.200)
    assert d2["per_device"]["/device:TPU:3"]["straggler_score"] == row["z"]
    assert d2["per_device"]["/device:TPU:3"]["straggler_steps"] == [0]
    # skew is max-min busy
    assert d2["steps"][0]["skew_ms"] == pytest.approx(0.100)


def test_straggler_min_slowdown_gate():
    """A statistically-significant but tiny delta must not flag — the
    sentinel's two-gate posture (z AND min_slowdown)."""
    d = timeline.decompose(_mesh_step_events([100, 100, 100, 110]))
    assert d["stragglers"] == []               # 1.1x < 1.2x floor


def test_observe_exports_gauges_and_straggler_events():
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    evs = [
        dev("fusion.1", 0, 100, device=0),
        dev("all-reduce.2", 50, 100, device=0),
        dev("fusion.1", 0, 300, device=1),     # straggler vs device 0
        dev("fusion.1", 0, 100, device=2),
        dev("fusion.1", 0, 100, device=3),
    ]
    d = timeline.decompose(evs)
    timeline.observe(d, reg)
    records = reg.flush()
    assert records_violations(records) == []    # schema-valid through
    gauges = {r["name"]: r["value"] for r in records
              if r.get("kind") == "metric" and r.get("type") == "gauge"}
    n = sum(x["steps"] for x in d["per_device"].values())
    assert gauges["step.device_compute_ms"] == \
        pytest.approx(d["totals"]["compute_ms"] / n)
    assert gauges["step.exposed_comm_ms"] == \
        pytest.approx(d["totals"]["exposed_comm_ms"] / n)
    assert gauges["step.device_idle_ms"] == \
        pytest.approx(d["totals"]["idle_ms"] / n)
    assert gauges["step.exposed_comm_fraction"] == \
        pytest.approx(d["totals"]["exposed_comm_fraction"])
    events = [r for r in records if r.get("kind") == "event"
              and r["name"] == "timeline.straggler"]
    assert len(events) == 1
    assert events[0]["fields"]["device"] == "/device:TPU:1"


def test_observe_disabled_registry_is_noop():
    reg = Registry(sink=MemorySink(), enabled=False)
    timeline.observe(timeline.decompose([dev("fusion.1", 0, 10)]), reg)
    timeline.observe(timeline.decompose([dev("fusion.1", 0, 10)]), None)
    assert reg.flush() == []


# ---------------------------------------------------------------------------
# merged host + device timeline (shared epoch anchor)
# ---------------------------------------------------------------------------

def test_merge_host_device_shared_anchor_and_windows():
    tr = trace.Tracer()
    with tr.span("train.step", step=1):
        pass
    doc = tr.export()
    dev_evs = [dev("fusion.1", 5000, 100), dev("all-reduce.1", 5100, 50)]
    merged = timeline.merge_host_device(doc, dev_evs)
    evs = merged["traceEvents"]
    names = {e.get("name") for e in evs if e.get("ph") == "X"}
    assert {"train.step", "fusion.1", "all-reduce.1"} <= names
    # the host span was rebased onto the device epoch (anchor: earliest
    # host event aligns with earliest device event)
    hostspan = next(e for e in evs if e.get("name") == "train.step")
    assert hostspan["ts"] == pytest.approx(5000.0)
    # device lanes keep their pids; the host got a fresh one
    devspan = next(e for e in evs if e.get("name") == "fusion.1")
    assert hostspan["pid"] != devspan["pid"]
    procs = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert "host:apex_tpu" in procs and "/device:TPU:0" in procs
    # and the merged doc round-trips the parser: host step windows now
    # segment the device activity
    from apex_tpu.pyprof import parse
    d = timeline.decompose(parse.events_from_chrome(evs))
    assert d["n_steps"] >= 1 and d["devices"] == ["/device:TPU:0"]


# ---------------------------------------------------------------------------
# profiler-dir fixture + CLI
# ---------------------------------------------------------------------------

def _write_profiler_dir(root, trace_events):
    """A jax-profiler run-dir fixture: the TensorBoard layout
    ``<dir>/plugins/profile/<run>/<host>.trace.json.gz``."""
    d = os.path.join(str(root), "plugins", "profile", "run_1")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "host.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"displayTimeUnit": "ms", "traceEvents": trace_events},
                  f)
    return path


def _chrome(name, ts, dur, pid, tid=1):
    return {"ph": "X", "name": name, "ts": ts, "dur": dur, "pid": pid,
            "tid": tid, "args": {}}


def _fixture_trace_events():
    return [
        {"ph": "M", "name": "process_name", "pid": 10,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 11,
         "args": {"name": "/device:TPU:1"}},
        {"ph": "M", "name": "thread_name", "pid": 10, "tid": 1,
         "args": {"name": "XLA Op"}},
        _chrome("fusion.1", 0, 100, 10),
        _chrome("all-reduce.2", 50, 100, 10),    # 50us exposed
        _chrome("fusion.1", 0, 100, 11),
        _chrome("all-reduce.2", 20, 40, 11),     # hidden
    ]


def test_summarize_profiler_dir_fixture(tmp_path):
    _write_profiler_dir(tmp_path, _fixture_trace_events())
    d = timeline.summarize(str(tmp_path))
    assert d["devices"] == ["/device:TPU:0", "/device:TPU:1"]
    assert d["totals"]["exposed_comm_ms"] == pytest.approx(0.050)
    assert d["totals"]["exposed_comm_fraction"] == \
        pytest.approx(0.050 / 0.140)


def test_cli_timeline_renders_table_and_json(tmp_path):
    """``python -m apex_tpu.telemetry timeline <profiler-dir>``: the
    per-step decomposition table + per-device skew section; ``--json``
    emits the machine form the tpu_watch.sh stage captures."""
    _write_profiler_dir(tmp_path, _fixture_trace_events())
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT}
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.telemetry", "timeline",
         str(tmp_path)],
        capture_output=True, text=True, cwd=ROOT, timeout=180, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "device timeline decomposition" in r.stdout
    assert "exposed" in r.stdout and "per-device skew" in r.stdout
    assert "/device:TPU:0" in r.stdout
    rj = subprocess.run(
        [sys.executable, "-m", "apex_tpu.telemetry", "timeline",
         str(tmp_path), "--json"],
        capture_output=True, text=True, cwd=ROOT, timeout=180, env=env)
    assert rj.returncode == 0, rj.stderr[-2000:]
    doc = json.loads(rj.stdout)
    assert doc["kind"] == "device_timeline"
    assert doc["totals"]["exposed_comm_ms"] == pytest.approx(0.050)


def test_cli_timeline_no_device_lanes_rc1(tmp_path):
    p = tmp_path / "hostonly.json"
    p.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "name": "$frame", "ts": 0, "dur": 10, "pid": 1,
         "tid": 1, "args": {}}]}))
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.telemetry", "timeline", str(p)],
        capture_output=True, text=True, cwd=ROOT, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT})
    assert r.returncode == 1
    assert "no device lanes" in r.stdout


# ---------------------------------------------------------------------------
# the overlap tuning loop: artifact -> decide() -> tuning -> plan
# ---------------------------------------------------------------------------

@pytest.fixture
def profile_file(tmp_path, monkeypatch):
    path = tmp_path / "tuned.json"
    monkeypatch.setenv("APEX_TPU_TUNING_FILE", str(path))
    tuning.reload()
    yield path
    tuning.reload()


def _spmd_artifact(overlap):
    return {"metric": "m", "value": 1.0, "unit": "ms",
            "vs_baseline": 1.0, "backend": "tpu",
            "detail": {"backend": "tpu",
                       "spmd": {"leg": "spmd", "chips": 8,
                                "families": {}, "overlap": overlap}}}


def test_overlap_roundtrip_decide_to_plan(profile_file):
    """The acceptance loop: a profiled-capture artifact's measured
    exposed-comm fraction -> decide() -> schema-valid
    tuned_defaults.json -> plan.predict charges only the exposed dp
    comm, changing the predicted step time."""
    mod = _load_apply()
    overlap = {"profile_dir": "SPMD_PROFILE_r5", "devices": 8, "steps": 1,
               "compute_ms": 10.0, "comm_ms": 4.0,
               "exposed_comm_ms": 1.0, "idle_ms": 0.5,
               "exposed_comm_fraction": 0.25, "stragglers": 0}
    prof, rows = mod.decide(_spmd_artifact(overlap), None)
    assert prof["overlap_measured_fraction"] == 0.25
    assert any("overlap_measured_fraction" in r[0] for r in rows)
    assert tuning.schema_violations(prof) == []
    # the audit passes a consistent block
    assert mod.overlap_violations(_spmd_artifact(overlap)) == []

    # persist -> consume: predict() under the tuned fraction charges
    # 0.25x the modeled dp comm
    from apex_tpu.parallel import plan as planmod
    prof_model = planmod.ModelProfile(
        name="oracle", flops=1e12, bytes_accessed=1e11,
        params_bytes=400 << 20, optimizer_bytes=800 << 20,
        activations_bytes=1 << 30, batch_bytes=64 << 20,
        temps_bytes=1 << 28, output_bytes=4096)
    p_full = planmod.predict(prof_model, planmod.Plan(dp=8),
                             platform="tpu")
    assert p_full.breakdown["overlap_fraction"] == 1.0
    assert p_full.breakdown["dp_comm_exposed_ms"] == \
        pytest.approx(p_full.breakdown["dp_comm_ms"])

    profile_file.write_text(json.dumps(prof))
    tuning.reload()
    p_tuned = planmod.predict(prof_model, planmod.Plan(dp=8),
                              platform="tpu")
    assert p_tuned.breakdown["overlap_fraction"] == 0.25
    assert p_tuned.breakdown["dp_comm_exposed_ms"] == \
        pytest.approx(0.25 * p_tuned.breakdown["dp_comm_ms"])
    # the overlap factor changes the predicted step time by exactly the
    # hidden comm
    hidden = p_full.breakdown["dp_comm_ms"] * 0.75
    assert p_full.predicted_step_ms - p_tuned.predicted_step_ms == \
        pytest.approx(hidden, rel=1e-6)
    # explicit argument beats the tuning profile
    p_exp = planmod.predict(prof_model, planmod.Plan(dp=8),
                            platform="tpu", overlap_fraction=0.5)
    assert p_exp.breakdown["overlap_fraction"] == 0.5


def test_overlap_env_pin_beats_tuning(profile_file, monkeypatch):
    profile_file.write_text(json.dumps({"overlap_measured_fraction": 0.3}))
    tuning.reload()
    assert timeline and tuning.get("overlap_measured_fraction") == 0.3
    from apex_tpu.parallel import plan as planmod
    assert planmod.resolve_overlap_fraction() == 0.3
    monkeypatch.setenv(planmod.ENV_OVERLAP, "0.7")
    assert planmod.resolve_overlap_fraction() == 0.7
    assert planmod.resolve_overlap_fraction(0.1) == 0.1   # arg wins
    # clamped to [0, 1]
    assert planmod.resolve_overlap_fraction(7.0) == 1.0


def test_decide_skips_unmeasured_or_commfree_overlap():
    mod = _load_apply()
    # an honestly-failed capture never decides
    prof, _ = mod.decide(_spmd_artifact({"error": "no profiler"}), None)
    assert "overlap_measured_fraction" not in prof
    # a comm-free capture (fraction None) never decides
    prof, _ = mod.decide(_spmd_artifact(
        {"compute_ms": 5.0, "comm_ms": 0.0, "exposed_comm_ms": 0.0,
         "exposed_comm_fraction": None}), None)
    assert "overlap_measured_fraction" not in prof


def test_overlap_violations_flag_inconsistent_blocks():
    mod = _load_apply()
    bad = _spmd_artifact({"compute_ms": 1.0, "comm_ms": 2.0,
                          "exposed_comm_ms": 3.0,     # > comm: impossible
                          "exposed_comm_fraction": 1.5})
    out = mod.overlap_violations(bad)
    assert any("exposed_comm_ms" in v for v in out)
    assert any("exposed_comm_fraction" in v for v in out)
    # error-only blocks pass (honest failure)
    assert mod.overlap_violations(_spmd_artifact({"error": "x"})) == []


# ---------------------------------------------------------------------------
# the bench capture helper (real profiler; skips where unavailable)
# ---------------------------------------------------------------------------

def test_bench_profiled_overlap_capture_real_profiler(tmp_path):
    """bench._profiled_overlap_capture drives a REAL jax.profiler
    window around one jitted step and decomposes the capture — the
    CPU-mesh flagship acceptance path, scaled to a toy psum step."""
    import jax
    import jax.numpy as jnp
    import bench

    mesh_step = jax.jit(lambda x: x * 2.0 + jnp.sum(x))
    x = jnp.ones((256, 256))
    mesh_step(x).block_until_ready()              # compile outside capture

    def one_step():
        mesh_step(x).block_until_ready()

    d = str(tmp_path / "cap")
    block, decomp = bench._profiled_overlap_capture(one_step, d)
    if "error" in block:
        pytest.skip(f"profiler capture unavailable: {block['error']}")
    assert block["profile_dir"] == d
    assert block["devices"] >= 1 and decomp is not None
    assert block["compute_ms"] >= 0.0
    # fraction is None (no collectives in this step) or within [0,1]
    frac = block["exposed_comm_fraction"]
    assert frac is None or 0.0 <= frac <= 1.0
    # a schema-valid leg shape: the audit accepts it
    mod = _load_apply()
    assert mod.overlap_violations({"overlap": block}) == []


# ---------------------------------------------------------------------------
# sentinel: capture-close feeds the decomposition into a flight dump
# ---------------------------------------------------------------------------

def test_sentinel_capture_close_attaches_timeline_dump(monkeypatch,
                                                       tmp_path):
    """When the one-shot profiler window closes, the sentinel feeds the
    capture through the timeline decomposition and dumps the per-step
    table as a ``slow_step_timeline`` flight document — the slow-step
    dump says WHEN, this one says WHERE the device time went."""
    import jax
    prof_dir = tmp_path / "anomaly"
    prof_dir.mkdir()
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    # the fake stop writes what a real flush would: a run-dir capture
    monkeypatch.setattr(
        jax.profiler, "stop_trace",
        lambda: _write_profiler_dir(prof_dir, _fixture_trace_events()))
    tr = trace.Tracer(flight_dir=str(tmp_path / "flight"))
    s = trace.SlowStepSentinel(window=16, warmup=8, z_threshold=4.0,
                               profile_dir=str(prof_dir),
                               profile_steps=2)
    for i in range(12):
        s.observe(i, 1e-2, tracer=tr)
    info = s.observe(12, 5e-2, tracer=tr)
    assert info["profile_started"] is True
    s.observe(13, 1e-2, tracer=tr)
    s.observe(14, 1e-2, tracer=tr)                # window closes here
    import atexit
    atexit.unregister(s.stop_capture)
    import glob
    dumps = glob.glob(str(tmp_path / "flight" /
                          "flight-slow_step_timeline-*.json"))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert trace.dump_violations(doc) == []       # core schema intact
    tl = doc["timeline"]
    assert tl["decomposition"]["totals"]["exposed_comm_ms"] == \
        pytest.approx(0.050)
    assert "device timeline decomposition" in tl["table"]
    assert doc["fields"]["n_devices"] == 2


def test_sentinel_capture_close_without_trace_is_silent(monkeypatch,
                                                        tmp_path):
    """An empty capture dir (profiler flushed nothing) must not dump a
    timeline document nor raise — best-effort all the way down."""
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    tr = trace.Tracer(flight_dir=str(tmp_path))
    s = trace.SlowStepSentinel(window=16, warmup=8, z_threshold=4.0,
                               profile_dir=str(tmp_path / "empty"),
                               profile_steps=1)
    for i in range(12):
        s.observe(i, 1e-2, tracer=tr)
    assert s.observe(12, 5e-2, tracer=tr)["profile_started"] is True
    s.observe(13, 1e-2, tracer=tr)
    import atexit
    atexit.unregister(s.stop_capture)
    import glob
    assert glob.glob(str(tmp_path / "flight-slow_step_timeline-*")) == []
