"""Auto-parallel planner (ISSUE 10) on the 8-device CPU mesh.

Covers the tentpole and its acceptance gates:

  * cost-model oracles pinned on hand-computable cases (2-chip ring
    allreduce alpha-beta time; a known-FLOPs matmul's roofline);
  * the search: >= 12 candidates enumerated for the flagship at 8
    simulated chips, every HBM-infeasible plan pruned (asserted
    against ``memory_model()``'s numbers), ties broken toward the
    simpler plan;
  * ``Plan.apply()`` reproducing the BITWISE-identical loss/params of
    the same manually-configured run (mesh + env knobs vs explicit
    args);
  * THE verify loop: ``bench.bench_plan`` measures the top predicted
    plans, the predicted pick lands within 25% of its calibrated
    prediction and no slower than the all-defaults baseline, and the
    winning knobs round-trip ``apply_perf_results.decide`` ->
    schema-valid ``tuned_defaults.json`` -> ``plan.from_tuning`` on
    the next run;
  * the ranked-table CLI (``python -m apex_tpu.parallel.plan``) from
    both a measured artifact and a fresh CPU cost-model run.
"""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.parallel import plan as pm
from apex_tpu.parallel import collectives
from apex_tpu.parallel import weight_update as wu
from apex_tpu.parallel.mesh import create_mesh
from apex_tpu.utils import tuning

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
N_DEV = 8

#: explicit ceilings for the oracle tests — no env / platform coupling
CEIL = {"peak_flops": 1e12, "peak_bw": 1e11, "ici_bw": 1e10,
        "ici_alpha_s": 1e-6, "hbm_bytes": 1e12}


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {k: os.environ.pop(k, None)
             for k in (collectives.ENV_KNOB, wu.ENV_KNOB,
                       "APEX_TPU_CEILINGS")}
    yield
    for k, v in saved.items():
        os.environ.pop(k, None)
        if v is not None:
            os.environ[k] = v


@pytest.fixture
def profile_file(tmp_path, monkeypatch):
    """Point the tuning profile at a temp file (test_tuning idiom)."""
    path = tmp_path / "tuned.json"

    def write(d):
        path.write_text(json.dumps(d))
        tuning.reload()

    monkeypatch.setenv("APEX_TPU_TUNING_FILE", str(path))
    tuning.reload()
    yield write
    monkeypatch.delenv("APEX_TPU_TUNING_FILE")
    tuning.reload()


@pytest.fixture
def fake_tpu(monkeypatch):
    jax.devices()                      # ensure backends_initialized()
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")


@pytest.fixture(scope="module")
def flagship():
    """(profile, cfg, global_batch, memory_model dict) for the tiny
    flagship step — the memory_model() is recomputed independently so
    the pruning assertions are against ITS numbers, not the profile's
    copy of them."""
    from apex_tpu.telemetry import memory as tmem
    cfg = pm._flagship_cfg(False)
    step, args = pm._flagship_step(cfg, 8)
    prof = pm.profile_step(step, *args, name="flagship-test", cfg=cfg,
                           global_batch=8)
    mm = tmem.memory_model(step, *args, register=False)
    return prof, cfg, 8, mm


def _synth_profile(**kw):
    base = dict(name="synth", flops=1e9, bytes_accessed=1e8,
                params_bytes=4096, optimizer_bytes=12288,
                activations_bytes=8192, batch_bytes=1024,
                temps_bytes=512, output_bytes=64, args_bytes=16,
                constants_bytes=8, peak_hbm_bytes=30000,
                layers=2, act_layer_bytes=4096, seq=64, heads=4,
                platform="cpu")
    base.update(kw)
    return pm.ModelProfile(**base)


# ---------------------------------------------------------------------------
# cost-model oracles
# ---------------------------------------------------------------------------

def test_collective_time_oracle_2chip_ring_allreduce():
    """Hand-computed 2-chip ring allreduce: 2(N-1) hops of alpha +
    2(N-1)/N of the payload over the link."""
    logical = 4 * (1 << 20)            # 1M fp32 elems
    t = pm.collective_time_s("all_reduce", logical, 2, CEIL)
    assert t == pytest.approx(2 * 1e-6 + 1.0 * logical / 1e10)
    # reduce-scatter / allgather: half the hops, half the traffic
    t_rs = pm.collective_time_s("reduce_scatter", logical, 2, CEIL)
    assert t_rs == pytest.approx(1e-6 + 0.5 * logical / 1e10)
    assert pm.collective_time_s("all_gather", logical, 2, CEIL) == t_rs
    # degenerate axes cost nothing
    assert pm.collective_time_s("all_reduce", logical, 1, CEIL) == 0.0
    assert pm.collective_time_s("all_reduce", 0, 8, CEIL) == 0.0
    with pytest.raises(ValueError, match="unknown collective"):
        pm.collective_time_s("gossip", logical, 2, CEIL)


def test_collective_time_scheme_wire_and_codec():
    """int8_blockscale ships the metered wire bytes (codes + scales)
    and pays its dequant-sum codec against HBM bandwidth — so it wins
    on slow wires and loses when the wire is as fast as memory."""
    logical = 4 * (1 << 20)
    nelems = logical // 4
    world = 8
    wire = collectives.wire_bytes("int8_blockscale", nelems)
    expected = (2 * (world - 1) * CEIL["ici_alpha_s"]
                + 2.0 * (world - 1) / world * wire / CEIL["ici_bw"]
                + (1 + world) * logical / CEIL["peak_bw"])
    t8 = pm.collective_time_s("all_reduce", logical, world, CEIL,
                              "int8_blockscale")
    assert t8 == pytest.approx(expected)
    t32 = pm.collective_time_s("all_reduce", logical, world, CEIL)
    assert t8 < t32                    # wire 10x slower than HBM: wins
    fast_wire = dict(CEIL, ici_bw=CEIL["peak_bw"])
    assert pm.collective_time_s(
        "all_reduce", logical, world, fast_wire, "int8_blockscale") > \
        pm.collective_time_s("all_reduce", logical, world, fast_wire)


def test_compute_time_known_flops_matmul():
    """The parse->model chain on a known workload: a 64x64x64 matmul is
    exactly 2*M*N*K FLOPs, and the compute-bound roofline time is
    flops/peak."""
    a = jnp.ones((64, 64), jnp.float32)
    prof = pm.profile_step(lambda x, y: x @ y, a, a, name="matmul")
    assert prof.flops == pytest.approx(2 * 64 ** 3, rel=0.01)
    t = pm.compute_time_s(prof.flops, 0.0, CEIL)
    assert t == pytest.approx(prof.flops / CEIL["peak_flops"])
    # bandwidth-bound when bytes dominate
    assert pm.compute_time_s(0.0, 1e9, CEIL) == pytest.approx(1e9 / 1e11)


def test_profile_step_surfaces_compiled_collectives():
    """The profile carries the compiled program's real collective
    payloads (the attrib sub-table) for comm-model calibration."""
    from jax.sharding import PartitionSpec as P
    from apex_tpu.parallel.mesh import shard_map
    mesh = create_mesh({"data": N_DEV})
    sm = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                   in_specs=(P("data"),), out_specs=P("data"))
    prof = pm.profile_step(sm, jnp.ones((N_DEV, 1024)), name="psum")
    ar = prof.collective_bytes["all-reduce"]
    assert ar["logical_bytes"] == 1024 * 4


# ---------------------------------------------------------------------------
# HBM model + search
# ---------------------------------------------------------------------------

def test_hbm_scaling_semantics():
    """Per-class scaling: tp shards params+optimizer, dp shards the
    optimizer ONLY when the update is sharded (the
    ``update_sharding_world`` semantics), activations/temps shard over
    every axis, batch over dp."""
    prof = _synth_profile()
    total, by = pm.plan_hbm_bytes(prof, pm.Plan(dp=8))
    assert by["params"] == 4096            # replicated over dp
    assert by["optimizer"] == 12288        # replicated: update is not sharded
    assert by["activations"] == 8192 // 8
    assert by["batch"] == 1024 // 8
    assert total == sum(by.values())
    _, by_z = pm.plan_hbm_bytes(prof, pm.Plan(dp=8,
                                              update_sharding="zero1"))
    assert by_z["optimizer"] == 12288 // 8
    _, by_tp = pm.plan_hbm_bytes(prof, pm.Plan(dp=4, tp=2))
    assert by_tp["params"] == 4096 // 2
    assert by_tp["optimizer"] == 12288 // 2
    assert by_tp["activations"] == 8192 // 8


def test_enumerate_flagship_8chips_ge_12_candidates(flagship):
    """ACCEPTANCE: the flagship at 8 simulated chips enumerates >= 12
    candidate plans spanning the axes."""
    prof, _, _, _ = flagship
    plans = pm.enumerate_plans(prof, N_DEV, platform="cpu")
    assert len(plans) >= 12
    assert all(p.chips == N_DEV for p in plans)
    assert any(p.tp > 1 for p in plans)                 # dp x tp plane
    assert any(p.zero for p in plans)                   # ZeRO on/off
    assert any(p.update_sharding == "zero1" for p in plans)
    schemes = {p.collective_scheme for p in plans if p.dp > 1}
    assert schemes == set(pm.PLAN_SCHEMES)
    # short sequences enumerate no SP plans ...
    assert all(p.sp == 1 for p in plans)
    # ... long sequences do (ring always; ulysses when heads divide)
    long = _synth_profile(seq=4096, heads=8)
    sp_plans = [p for p in pm.enumerate_plans(long, N_DEV,
                                              platform="cpu")
                if p.sp > 1]
    assert {p.sp_strategy for p in sp_plans} == {"ring", "ulysses"}


def test_search_prunes_all_infeasible_against_memory_model(flagship):
    """Property: ``search`` NEVER returns an HBM-infeasible plan.  The
    capacity is squeezed until some candidates are infeasible, and
    feasibility is recomputed here from ``memory_model()``'s own
    numbers — not trusted from the search."""
    prof, _, _, mm = flagship
    # the profile's memory facts ARE memory_model()'s (no drift)
    assert prof.params_bytes == mm["params_bytes"]
    assert prof.optimizer_bytes == mm["optimizer_bytes"]
    assert prof.activations_bytes == mm["activations_bytes"]
    all_plans = pm.enumerate_plans(prof, N_DEV, platform="cpu")
    demands = sorted(p.predicted_hbm_bytes for p in all_plans)
    cap = demands[len(demands) // 2]       # median: some must be pruned
    ranked = pm.search(prof, N_DEV, platform="cpu", capacity_bytes=cap)
    assert ranked and len(ranked) < len(all_plans)

    def hbm_from_memory_model(p):
        pp, ep = p.pp_stages, p.ep
        opt_div = p.tp * pp * (p.dp if p.shards_update else 1)
        total = (mm["params_bytes"] // (p.tp * pp)
                 + mm["optimizer_bytes"] // opt_div
                 + mm["activations_bytes"] // (p.dp * p.tp * p.sp * pp * ep)
                 + mm["batch_bytes"] // (p.dp * p.sp * ep)
                 + mm["temps_bytes"] // (p.dp * p.tp * p.sp * ep)
                 + mm["output_bytes"] // (p.dp * ep)
                 + mm["args_bytes"] + mm["constants_bytes"])
        if pp > 1:        # GPipe stash: one block/tick + M output slots
            m = max(int(p.pp_microbatches), 1)
            total += (m + pp - 1 + m) * (
                prof.act_layer_bytes // max(p.dp * m, 1))
        if ep > 1:        # dispatch/combine one-hots + a2a queues, f32
            e, cap_, d, t_loc = pm._ep_geometry(prof, p.dp, ep, p.sp)
            total += 4 * (2 * t_loc * e * cap_ + 2 * e * cap_ * d)
        return total

    for p in ranked:
        assert hbm_from_memory_model(p) <= cap, p.describe()
    assert any(hbm_from_memory_model(p) > cap for p in all_plans)


def test_tie_break_prefers_simpler_plan():
    """Predictions inside the tie band resolve to the SIMPLEST plan:
    with negligible params (no wire, no update to shrink) every dp=8
    variant predicts the same, and the all-defaults baseline must rank
    first."""
    prof = _synth_profile(params_bytes=512, optimizer_bytes=1536,
                          layers=0)
    ranked = pm.search(prof, N_DEV, ceilings=CEIL)
    assert ranked[0].knobs() == pm.default_plan(N_DEV).knobs()


def test_int8_wins_on_tpu_wire_loses_on_cpu(flagship):
    """The codec model makes compression platform-aware: on TPU
    ceilings (ICI far slower than HBM) the int8 dp wire beats fp32; on
    the CPU-emulated mesh (wire ~ memory) it loses."""
    prof, _, _, _ = flagship

    def dp_comm(platform, scheme):
        p = pm.predict(prof, pm.Plan(dp=N_DEV,
                                     collective_scheme=scheme),
                       platform=platform)
        return p.breakdown["dp_comm_ms"]

    assert dp_comm("tpu", "int8_blockscale") < dp_comm("tpu", "fp32")
    assert dp_comm("cpu", "int8_blockscale") > dp_comm("cpu", "fp32")


# ---------------------------------------------------------------------------
# pp / ep families (ISSUE 17)
# ---------------------------------------------------------------------------

def test_enumerate_pp_ep_candidates(flagship):
    """ACCEPTANCE: the flagship at 8 chips enumerates >= 2 pp and >= 2
    ep candidates, and every structural constraint holds: stages
    divide the layer stack, M divides the per-replica batch, the ep
    width divides the expert count, both compose with dp only, and
    both run the plain fused-flat update (no zero/zero1 variants — the
    engine cannot run them)."""
    prof, _, _, _ = flagship
    plans = pm.enumerate_plans(prof, N_DEV, platform="cpu")
    pps = [p for p in plans if p.pp_stages > 1]
    eps = [p for p in plans if p.ep > 1]
    assert len(pps) >= 2 and len(eps) >= 2
    for p in pps:
        assert prof.layers % p.pp_stages == 0
        assert (prof.global_batch // p.dp) % p.pp_microbatches == 0
        assert p.tp == p.sp == p.ep == 1
        assert not p.zero and p.update_sharding == "off"
        assert p.family == "pp" and p.measurable
    for p in eps:
        e_total = prof.experts or pm.EP_DEFAULT_EXPERTS
        assert e_total % p.ep == 0
        assert p.tp == p.sp == p.pp_stages == 1
        assert not p.zero and p.update_sharding == "off"
        assert p.family == "ep" and p.measurable
    # the microbatch lattice actually varies — the bubble knob is
    # searched, not pinned
    assert len({p.pp_microbatches for p in pps}) >= 2
    # knob rendering for tables/logs
    assert pm.Plan(dp=4, pp_stages=2,
                   pp_microbatches=2).describe() == "dp=4 pp=2x2"
    assert pm.Plan(dp=4, ep=2).describe() == "dp=4 ep=2"


def test_pp_cost_model_bubble_and_wire_oracle():
    """GPipe oracle: the bubble charges ``t_train * (S-1)/M`` on the
    critical path (shrinking as M grows) and the wire charges
    ``2(M+S-1)`` stage-hop ppermutes of one microbatch activation
    block; dense plans charge nothing."""
    prof = _synth_profile(global_batch=8)
    p = pm.predict(prof, pm.Plan(dp=4, pp_stages=2, pp_microbatches=2),
                   ceilings=CEIL)
    bd = p.breakdown
    assert bd["pp_bubble_ms"] == pytest.approx(bd["train_ms"] / 2)
    blk = prof.act_layer_bytes / (4 * 2)
    want_s = 2 * (2 + 2 - 1) * pm.collective_time_s("ppermute", blk, 2,
                                                    CEIL)
    assert bd["pp_comm_ms"] == pytest.approx(want_s * 1e3)
    p1 = pm.predict(prof, pm.Plan(dp=4, pp_stages=2, pp_microbatches=1),
                    ceilings=CEIL)
    assert p1.breakdown["pp_bubble_ms"] > bd["pp_bubble_ms"]
    dense = pm.predict(prof, pm.Plan(dp=8), ceilings=CEIL).breakdown
    assert dense["pp_bubble_ms"] == dense["pp_comm_ms"] == 0.0


def test_ep_cost_model_capacity_wire_and_hlo_subtable():
    """ep oracle: the router wire charges 4 capacity-factored
    all_to_alls per layer (the owner-major ``(E*C, D)`` queue both
    ways, forward + the mirrored backward); a compiled-HLO all-to-all
    sub-table, when the profile carries one, overrides the analytic
    formula (measured bytes beat modeled bytes)."""
    prof = _synth_profile(global_batch=8, experts=8)
    p = pm.predict(prof, pm.Plan(dp=4, ep=2), ceilings=CEIL)
    e, cap, d_model, _ = pm._ep_geometry(prof, 4, 2)
    a2a = 4.0 * e * cap * d_model
    want_s = 4 * prof.layers * pm.collective_time_s("all_to_all", a2a,
                                                    2, CEIL)
    assert p.breakdown["ep_comm_ms"] == pytest.approx(want_s * 1e3)
    prof2 = _synth_profile(global_batch=8, experts=8, collective_bytes={
        "all-to-all": {"logical_bytes": 1 << 20, "count": 4}})
    p2 = pm.predict(prof2, pm.Plan(dp=4, ep=2), ceilings=CEIL)
    want2_s = 2 * 4 * pm.collective_time_s("all_to_all", (1 << 20) / 4,
                                           2, CEIL)
    assert p2.breakdown["ep_comm_ms"] == pytest.approx(want2_s * 1e3)
    dense = pm.predict(prof, pm.Plan(dp=8), ceilings=CEIL)
    assert dense.breakdown["ep_comm_ms"] == 0.0


def test_hbm_charges_pp_stash_and_ep_buffers():
    """The HBM model charges pp its schedule stash (``(ticks + M)``
    microbatch activation blocks) and ep its expert-capacity buffers
    (dispatch/combine one-hots + both all_to_all queues, fp32); dense
    plans carry neither class; params shard over the stage axis."""
    prof = _synth_profile(global_batch=8, experts=8)
    _, by_pp = pm.plan_hbm_bytes(
        prof, pm.Plan(dp=4, pp_stages=2, pp_microbatches=2))
    ticks = 2 + 2 - 1
    blk = prof.act_layer_bytes // (4 * 2)
    assert by_pp["pp_stash"] == (ticks + 2) * blk
    assert by_pp["params"] == prof.params_bytes // 2
    _, by_ep = pm.plan_hbm_bytes(prof, pm.Plan(dp=4, ep=2))
    e, cap, d_model, t_local = pm._ep_geometry(prof, 4, 2)
    assert by_ep["ep_buffers"] == 4 * (2 * t_local * e * cap
                                       + 2 * e * cap * d_model)
    _, by_d = pm.plan_hbm_bytes(prof, pm.Plan(dp=8))
    assert "pp_stash" not in by_d and "ep_buffers" not in by_d


def test_search_prunes_infeasible_pp_ep(flagship):
    """The never-returns-infeasible property holds with pp/ep in the
    space: squeeze the capacity to the pp/ep demand median and every
    ranked plan — its HBM recomputed incl. the GPipe stash / expert
    buffers — still fits."""
    prof, _, _, _ = flagship
    all_plans = pm.enumerate_plans(prof, N_DEV, platform="cpu")
    ppep = [p for p in all_plans if p.pp_stages > 1 or p.ep > 1]
    assert ppep
    demands = sorted(p.predicted_hbm_bytes for p in ppep)
    assert demands[0] < demands[-1]    # the squeeze can discriminate
    cap = (demands[0] + demands[-1]) // 2
    ranked = pm.search(prof, N_DEV, platform="cpu", capacity_bytes=cap)
    assert ranked
    for p in ranked:
        total, by = pm.plan_hbm_bytes(prof, p)
        assert total <= cap, p.describe()
        if p.pp_stages > 1:
            assert "pp_stash" in by
        if p.ep > 1:
            assert "ep_buffers" in by
    assert any(p.predicted_hbm_bytes > cap for p in ppep)


# ---------------------------------------------------------------------------
# Plan.apply: env round-trip + the bitwise A/B
# ---------------------------------------------------------------------------

def test_apply_env_roundtrip(monkeypatch):
    """apply() engages exactly the plan's env knobs inside the context,
    masks conflicting ambient knobs, and restores everything after."""
    monkeypatch.setenv(collectives.ENV_KNOB, "bf16")   # ambient A/B var
    plan = pm.Plan(dp=N_DEV, update_sharding="zero1")
    with plan.apply() as mesh:
        assert dict(mesh.shape)["data"] == N_DEV
        assert os.environ.get(wu.ENV_KNOB) == "zero1"
        # the plan's fp32 wire means NO collectives knob — the ambient
        # one must not leak into the applied plan
        assert collectives.ENV_KNOB not in os.environ
    assert os.environ.get(collectives.ENV_KNOB) == "bf16"   # restored
    assert wu.ENV_KNOB not in os.environ
    plan8 = pm.Plan(dp=N_DEV, collective_scheme="int8_blockscale")
    with plan8.apply():
        assert os.environ[collectives.ENV_KNOB] == "int8_blockscale"
    assert os.environ.get(collectives.ENV_KNOB) == "bf16"


def _ab_cfg():
    return pm._flagship_cfg(False, num_layers=1, d_model=32, d_ff=64,
                            vocab_size=64, max_len=16, num_heads=2)


def _ab_batch(i):
    rng = np.random.RandomState(1000 + i)
    return jnp.asarray(rng.randint(0, 64, (N_DEV, 16)).astype("int32"))


@pytest.mark.parametrize("ddp_kwargs", [
    {}, {"update_sharding": "zero1"},
], ids=["all-defaults", "zero1"])
def test_apply_reproduces_manual_run_bitwise(ddp_kwargs):
    """ACCEPTANCE: training under ``plan.apply()`` (mesh + env knobs,
    knob-less DDP inside) is BITWISE the same run configured by hand
    (explicit mesh + explicit DDP args) — losses and params."""
    cfg = _ab_cfg()

    def run_manual():
        mesh = create_mesh({"data": N_DEV})
        carry, step = pm.build_flagship_step(cfg, mesh, global_batch=8,
                                             ddp_kwargs=ddp_kwargs)
        losses = []
        for i in range(3):
            carry, loss = step(carry, _ab_batch(i))
            losses.append(float(loss))
        return carry, losses

    def run_plan():
        plan = pm.Plan(dp=N_DEV,
                       update_sharding=ddp_kwargs.get("update_sharding",
                                                      "off"))
        with plan.apply() as mesh:
            carry, step = pm.build_flagship_step(cfg, mesh,
                                                 global_batch=8)
            losses = []
            for i in range(3):
                carry, loss = step(carry, _ab_batch(i))
                losses.append(float(loss))
        return carry, losses

    (pm_, _), lm = run_manual()
    (pp_, _), lp = run_plan()
    assert lm == lp
    assert lm[-1] < lm[0]              # training actually happened
    for (kp_a, a), (kp_b, b) in zip(
            jax.tree_util.tree_leaves_with_path(pm_),
            jax.tree_util.tree_leaves_with_path(pp_)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(kp_a))


# ---------------------------------------------------------------------------
# the verify/persist loop (bench.py --plan -> apply_perf_results ->
# tuned_defaults.json -> from_tuning)
# ---------------------------------------------------------------------------

def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_for_plan", os.path.join(ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_apply():
    spec = importlib.util.spec_from_file_location(
        "apply_perf_for_plan",
        os.path.join(ROOT, "tools", "apply_perf_results.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow   # ~120s: eleven real measured rows on the emulated
# mesh, and on a single-core host the family-calibration margins sit AT
# the 25% plan_violations bar (back-to-back runs of identical configs
# spread 10-40%) — the leg's mechanics (coverage-row selection, audit,
# decide() -> from_tuning round-trip) stay tier-1 through the synthetic
# planner tests above, and the real leg runs as watcher stage 2d
# (PLAN_AB_r5.json) where the TPU backend gives stable measurements
def test_bench_plan_acceptance_loop(profile_file, monkeypatch):
    """ACCEPTANCE: ``bench_plan`` on the CPU mesh — >= 12 candidates,
    the predicted-fastest plan's measured step time within 25% of its
    calibrated prediction and no slower than the all-defaults
    baseline, the artifact passes the drift-guard audit, and the
    winning knobs round-trip decide -> schema-valid tuned_defaults ->
    ``from_tuning`` on the 'next run'."""
    bench = _load_bench()
    out = bench.bench_plan(False, top_k=2, steps=2)
    assert out["candidates_enumerated"] >= 12
    assert out["feasible"] >= 1
    rows = out["plans"]
    assert len(rows) >= 2
    # rows[0] is the ranked pick (the leg's contract): within 25% of
    # its calibrated prediction, and no slower than the baseline
    top = rows[0]
    assert out["calibration_error_pct"] <= 25.0, out
    assert top["measured_ms"] <= out["baseline_step_ms"] * 1.0001, out
    # audit: no drift, telemetry well-formed
    mod = _load_apply()
    artifact = {"backend": "tpu", "detail": {"plan": out}}
    assert mod.plan_violations(artifact) == []
    from apex_tpu.telemetry import records_violations
    assert records_violations(out["telemetry"]["records"]) == []

    # persist: decide -> schema-valid profile -> consumed next run
    prof_keys, rows_tbl = mod.decide(artifact, None)
    plan_keys = {k: v for k, v in prof_keys.items()
                 if k.startswith("plan_")}
    assert plan_keys, rows_tbl
    assert tuning.schema_violations(prof_keys) == []
    profile_file(prof_keys)
    jax.devices()
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    tuned = pm.from_tuning(N_DEV)
    assert tuned is not None
    win = out["measured_winner"]
    assert tuned.dp == win["dp"]
    assert tuned.update_sharding == win["update_sharding"]
    assert tuned.collective_scheme == win["collective_scheme"]
    # a winner measured at another topology never applies
    assert pm.from_tuning(N_DEV * 2) is None


def test_from_tuning_posture(profile_file, fake_tpu):
    profile_file({"plan_dp": 8, "plan_update_sharding": "zero1"})
    p = pm.from_tuning(8)
    assert p is not None and p.update_sharding == "zero1"
    assert p.tp == 1 and p.collective_scheme == "fp32"   # defaults
    assert pm.from_tuning(4) is None                     # chips mismatch
    profile_file({})
    assert pm.from_tuning(8) is None                     # no plan keys


def test_from_tuning_pp_ep_roundtrip(profile_file, fake_tpu):
    """The pp/ep knobs round-trip tuned_defaults.json: schema-valid,
    consumed by ``from_tuning``, and the chip count includes the new
    axes (a 4x2 lattice IS an 8-chip plan)."""
    pp_keys = {"plan_dp": 4, "plan_pp_stages": 2,
               "plan_pp_microbatches": 2}
    assert tuning.schema_violations(pp_keys) == []
    profile_file(pp_keys)
    p = pm.from_tuning(N_DEV)
    assert p is not None and p.family == "pp"
    assert (p.pp_stages, p.pp_microbatches) == (2, 2)
    assert p.chips == N_DEV
    assert pm.from_tuning(4) is None       # dp alone is NOT the plan

    ep_keys = {"plan_dp": 4, "plan_ep": 2}
    assert tuning.schema_violations(ep_keys) == []
    profile_file(ep_keys)
    p = pm.from_tuning(N_DEV)
    assert p is not None and p.family == "ep" and p.ep == 2
    assert p.chips == N_DEV


def test_from_tuning_ignored_off_tpu(profile_file):
    """Measured winners apply where they were measured — the CPU
    backend must not consume a TPU-measured plan (tooling can opt in
    with tpu_only=False)."""
    profile_file({"plan_dp": 8})
    assert pm.from_tuning(8) is None
    assert pm.from_tuning(8, tpu_only=False) is not None


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_renders_artifact_and_fresh_run(tmp_path):
    """``python -m apex_tpu.parallel.plan`` renders the ranked table
    from a measured artifact AND from a fresh CPU cost-model run."""
    art = {"metric": "plan_ab", "backend": "cpu", "plan": {
        "leg": "plan", "chips": 8, "plans": [
            {"knobs": {"dp": 8, "update_sharding": "zero1"},
             "predicted_ms": 1.5, "measured_ms": 1.4,
             "hbm_bytes": 1 << 20},
            {"knobs": {"dp": 8}, "predicted_ms": 2.0,
             "measured_ms": 2.0, "hbm_bytes": 1 << 20}]}}
    path = tmp_path / "plan_ab.json"
    path.write_text(json.dumps(art))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT}
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.plan",
         "--artifact", str(path)],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "winner knobs" in r.stdout
    assert "us=zero1" in r.stdout
    assert "1.400" in r.stdout                 # measured column rendered

    r2 = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.plan",
         "--chips", "8", "--model", "flagship",
         "--layers", "1", "--seq", "16", "--batch", "8"],
        capture_output=True, text=True, env=env, timeout=300)
    assert r2.returncode == 0, r2.stderr
    assert "HBM-feasible" in r2.stdout
    assert "winner knobs" in r2.stdout
