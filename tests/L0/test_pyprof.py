"""pyprof shim tests — annotate API + the prof (cost-analysis) mode.

Reference analog: ``tests/L0/run_pyprof_nvtx`` / ``run_pyprof_data`` —
the profiler's API surface is unit-tested without a GPU profiler attached
(SURVEY §4).  Here: annotate works inside and outside jit, and
``prof.cost_report`` returns a sane FLOPs/bytes roofline report for a
known workload.
"""
import jax
import jax.numpy as jnp
import pytest

from apex_tpu import pyprof
from apex_tpu.pyprof import prof


def test_init_and_annotate_outside_jit(capsys):
    pyprof.init()
    assert pyprof.is_initialized()
    out = capsys.readouterr().out
    assert "jax.profiler" in out
    with pyprof.annotate("region", step=3):
        x = jnp.ones((4,)) * 2
    assert float(x.sum()) == 8.0


def test_annotate_inside_jit_names_scope():
    @jax.jit
    def f(x):
        with pyprof.annotate("hot_matmul"):
            return x @ x

    x = jnp.ones((8, 8))
    # the named scope must appear in the op metadata of the lowered module
    # (plain as_text() strips location info; debug_info keeps it)
    lowered = jax.jit(lambda x: f(x)).lower(x)
    try:
        hlo = lowered.as_text(debug_info=True)
    except TypeError:
        # pre-debug_info jax strips locations from the stablehlo text;
        # the compiled executable's HLO keeps op metadata either way
        hlo = "\n".join(m.to_string() for m in lowered.compile()
                        .runtime_executable().hlo_modules())
    assert "hot_matmul" in hlo
    assert float(f(x)[0, 0]) == 8.0


def test_annotate_function_decorator():
    @pyprof.annotate_function(name="wrapped")
    def g(x):
        return x + 1

    assert float(g(jnp.float32(1.0))) == 2.0


def test_cost_report_matmul_flops():
    n = 64

    def f(a, b):
        return a @ b

    a = jnp.ones((n, n), jnp.float32)
    rep = prof.cost_report(f, a, a)
    assert rep["platform"] == jax.devices()[0].platform
    # an n^3 matmul is 2*n^3 FLOPs; cost models may fold constants but
    # must land within 2x of the analytic count
    analytic = 2 * n ** 3
    assert analytic / 2 <= rep["flops"] <= analytic * 2, rep["flops"]
    assert rep["bytes_accessed"] > 0
    assert rep["arithmetic_intensity"] > 0
    assert rep["projected_ms"] > 0
    text = prof.format_report(rep)
    assert "flops" in text and "roofline" in text


def test_cost_report_scales_with_problem_size():
    def f(a, b):
        return a @ b

    small = prof.cost_report(f, jnp.ones((32, 32)), jnp.ones((32, 32)))
    big = prof.cost_report(f, jnp.ones((128, 128)), jnp.ones((128, 128)))
    # 4x dim => 64x flops
    assert big["flops"] > 10 * small["flops"]


def test_measured_vs_projected_runs():
    def f(a):
        return jnp.sum(a * 2.0)

    rep = prof.measured_vs_projected(f, jnp.ones((256, 256)), iters=3)
    assert rep["measured_ms"] > 0
    assert "utilisation" in rep


def test_trace_capture(tmp_path):
    d = str(tmp_path / "trace")
    try:
        with pyprof.trace(d):
            jnp.ones((16,)).sum().block_until_ready()
    except Exception as e:   # profiler unavailable in sandboxed CI
        pytest.skip(f"profiler capture unavailable: {e}")
    import os
    found = [f for _, _, fs in os.walk(d) for f in fs]
    assert found, "trace produced no files"


# ---- parse (trace -> per-op table) -----------------------------------------

def _fake_events():
    # one XLA thread: fusion(10..110us) containing dot(20..80us);
    # python thread span must be excluded by default
    return [
        {"name": "fusion.1", "ts": 10.0, "dur": 100.0, "pid": 1, "tid": 2,
         "process": "/device:TPU:0", "thread": "XLA Op", "args": {}},
        {"name": "dot.3", "ts": 20.0, "dur": 60.0, "pid": 1, "tid": 2,
         "process": "/device:TPU:0", "thread": "XLA Op", "args": {}},
        {"name": "$main.py:1 step", "ts": 0.0, "dur": 500.0, "pid": 1,
         "tid": 9, "process": "/host:CPU", "thread": "python", "args": {}},
    ]


def test_parse_self_time_nesting():
    from apex_tpu.pyprof import parse
    table = parse.op_table(_fake_events())
    by = {r["name"]: r for r in table}
    assert "$main.py:1 step" not in by          # python excluded by default
    assert by["dot.3"]["self_us"] == 60.0
    assert by["fusion.1"]["self_us"] == 40.0    # 100 - 60 child
    assert abs(sum(r["pct"] for r in table) - 100.0) < 1e-6
    txt = parse.format_table(table)
    assert "dot.3" in txt

    withpy = {r["name"]: r for r in parse.op_table(
        _fake_events(), include_python=True)}
    assert "$main.py:1 step" in withpy


def test_events_from_chrome_counts_dropped_events():
    """ISSUE 13 satellite: complete ("X") records missing ts/dur —
    a profiler killed mid-flush writes torn records — are DROPPED and
    counted into the returned list's ``dropped_events`` (mirroring the
    Tracer's ``droppedSpans``), never silently parsed as phantom spans
    at the trace origin."""
    from apex_tpu.pyprof import parse
    raw = [
        {"ph": "X", "name": "ok", "ts": 0.0, "dur": 5.0, "pid": 1,
         "tid": 1},
        {"ph": "X", "name": "no_dur", "ts": 1.0, "pid": 1, "tid": 1},
        {"ph": "X", "name": "no_ts", "dur": 2.0, "pid": 1, "tid": 1},
        {"ph": "C", "name": "counter", "pid": 1},   # not "X": not counted
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "p"}},
    ]
    evs = parse.events_from_chrome(raw)
    assert [e["name"] for e in evs] == ["ok"]
    assert evs.dropped_events == 2
    # a clean trace counts zero
    assert parse.events_from_chrome(raw[:1]).dropped_events == 0


def test_parse_equal_bound_twins_not_negative():
    """Two spans with identical (ts, dur) on one thread — seen in real
    Chrome traces for zero/equal-length nested spans — must not debit
    each other (arbitrary parent/child order was driving self_us
    negative and skewing pct)."""
    from apex_tpu.pyprof import parse
    evs = [
        {"name": "outer", "ts": 0.0, "dur": 100.0, "pid": 1, "tid": 2,
         "process": "/device:TPU:0", "thread": "tensorflow", "args": {}},
        {"name": "twin_a", "ts": 10.0, "dur": 20.0, "pid": 1, "tid": 2,
         "process": "/device:TPU:0", "thread": "tensorflow", "args": {}},
        {"name": "twin_b", "ts": 10.0, "dur": 20.0, "pid": 1, "tid": 2,
         "process": "/device:TPU:0", "thread": "tensorflow", "args": {}},
    ]
    table = parse.op_table(evs, include_noise=True)
    by = {r["name"]: r for r in table}
    # outer debited once for the twin pair; the twins resolve as a
    # (degenerate) parent/child chain with clamped debits — totals sum
    # to wall time, nothing goes negative
    assert by["outer"]["self_us"] == 80.0
    assert by["twin_a"]["self_us"] == 0.0
    assert by["twin_b"]["self_us"] == 20.0
    assert all(r["self_us"] >= 0 for r in table)
    assert sum(r["self_us"] for r in table) == 100.0


def test_parse_real_capture(tmp_path):
    from apex_tpu.pyprof import parse
    d = str(tmp_path / "tr")
    try:
        with pyprof.trace(d):
            for _ in range(2):
                (jnp.ones((128, 128)) @ jnp.ones((128, 128))
                 ).block_until_ready()
    except Exception as e:
        pytest.skip(f"profiler capture unavailable: {e}")
    events = parse.load(d)
    assert events, "trace parsed to zero events"
    table = parse.op_table(events)
    assert table, "no non-python ops in trace"
    # the matmul must show up on an XLA/runtime thread
    assert any("dot" in r["name"] for r in table), \
        [r["name"] for r in table[:10]]


def test_resolve_ceilings_generations_and_env(monkeypatch):
    """ISSUE 10 satellite: per-TPU-generation ceilings rows plus the
    documented APEX_TPU_CEILINGS override, so planner/roofline
    predictions aren't pinned to the single generic "tpu" row."""
    monkeypatch.delenv(prof.ENV_CEILINGS, raising=False)
    # every row carries the full silicon key set (the planner reads all
    # of them); num_slices is topology, override-only — a row carrying
    # it would defeat plan.search()'s live-mesh detection (ISSUE 12)
    for name, row in prof.HW_CEILINGS.items():
        assert set(row) == set(prof.CEILING_KEYS) - {"num_slices"}, name
    monkeypatch.setenv(prof.ENV_CEILINGS, "num_slices=2")
    assert prof.resolve_ceilings("tpu")["num_slices"] == 2
    monkeypatch.delenv(prof.ENV_CEILINGS)
    # the generic tpu row stays the v5e chip the r5 runs measured on
    assert prof.HW_CEILINGS["tpu"] == prof.HW_CEILINGS["tpu_v5e"]
    assert prof.resolve_ceilings("tpu") == prof.HW_CEILINGS["tpu"]
    # unknown platform falls back to the cpu row (attrib posture)
    assert prof.resolve_ceilings("quantum") == prof.HW_CEILINGS["cpu"]
    # named-row override (shorthand resolves to the tpu_* row)
    monkeypatch.setenv(prof.ENV_CEILINGS, "v5p")
    assert prof.resolve_ceilings("tpu")["peak_flops"] == \
        prof.HW_CEILINGS["tpu_v5p"]["peak_flops"]
    # row + key override, applied left to right
    monkeypatch.setenv(prof.ENV_CEILINGS, "v4,ici_bw=5e10")
    c = prof.resolve_ceilings("tpu")
    assert c["peak_bw"] == prof.HW_CEILINGS["tpu_v4"]["peak_bw"]
    assert c["ici_bw"] == 5e10
    # a typo'd key or row fails loudly, never silently
    monkeypatch.setenv(prof.ENV_CEILINGS, "peak_floops=1e12")
    with pytest.raises(ValueError, match="unknown ceiling"):
        prof.resolve_ceilings("tpu")
    monkeypatch.setenv(prof.ENV_CEILINGS, "v9000")
    with pytest.raises(ValueError, match="unknown ceilings row"):
        prof.resolve_ceilings("tpu")
