"""Distributed-layer tests on the 8-device CPU mesh (conftest forces
``xla_force_host_platform_device_count=8``) — the fake-cluster capability the
reference's real-multiprocess harness lacked (SURVEY §4 takeaway).

Oracles follow the reference's pattern: SyncBN vs a single-device whole-batch
computation (``tests/distributed/synced_batchnorm/two_gpu_unit_test.py``),
DDP grad allreduce vs analytically-known sums
(``tests/distributed/DDP/ddp_race_condition_test.py:28-70``), LARC vs a
hand-written update (``tests/L0/run_amp/test_larc.py``).
"""
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from apex_tpu.parallel.mesh import shard_map  # jax-version compat

from apex_tpu import parallel
from apex_tpu.parallel import (
    DistributedDataParallel, Reducer, LARC, SyncBatchNorm,
    sync_batch_norm, create_mesh, create_grouped_mesh, use_mesh)
from apex_tpu.optimizers import FusedSGD


N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    return create_mesh({"data": N_DEV})


def test_ddp_allreduce_grads_mean(mesh):
    """Grad psum averages across the data axis (distributed.py:446-455)."""
    ddp = DistributedDataParallel(axis_name="data")
    local = jnp.arange(N_DEV, dtype=jnp.float32)  # device i holds value i

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def reduce(x):
        grads = {"w": x}
        return ddp.allreduce_grads(grads)["w"]

    out = reduce(local)
    expected = np.full(N_DEV, np.mean(np.arange(N_DEV)), np.float32)
    np.testing.assert_allclose(np.asarray(out), expected)


def test_ddp_predivide_and_fp32_allreduce(mesh):
    """predivide_factor: divide by f pre-reduce, f/world post (:446-455);
    allreduce_always_fp32 upcasts bf16 for the reduce (:443-445)."""
    ddp = DistributedDataParallel(axis_name="data",
                                  gradient_predivide_factor=2.0,
                                  allreduce_always_fp32=True)
    local = jnp.ones((N_DEV,), jnp.bfloat16) * 3

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def reduce(x):
        return ddp.allreduce_grads({"w": x})["w"]

    out = reduce(local)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), 3.0)


def test_ddp_noop_outside_mesh():
    ddp = DistributedDataParallel(axis_name="data")
    g = {"w": jnp.ones((4,))}
    out = ddp.allreduce_grads(g)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)


def test_ddp_noop_knobs_warn():
    # multi-stream options remain documented no-ops (XLA owns stream
    # scheduling) ...
    with pytest.warns(UserWarning):
        DistributedDataParallel(axis_name="data", num_allreduce_streams=2)
    # ... but message_size is LIVE again since the async-overlap work
    # (parallel.overlap bucket threshold) — it must NOT warn
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        ddp = DistributedDataParallel(axis_name="data", message_size=1)
    assert ddp.message_size == 1


def test_reducer_sum_vs_known(mesh):
    """Analytically-known reduction (ddp_race_condition_test.py pattern)."""
    red = Reducer(axis_name="data", gradient_average=False)
    local = jnp.arange(N_DEV, dtype=jnp.float32)

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def reduce(x):
        return red.reduce(x)

    out = reduce(local)
    np.testing.assert_allclose(np.asarray(out), float(np.arange(N_DEV).sum()))


# ---------------------------------------------------------------------------
# SyncBatchNorm
# ---------------------------------------------------------------------------

def _bn_oracle(x, w, b, eps=1e-5):
    """Whole-batch NHWC batchnorm in numpy (fp64 accumulate) — the oracle of
    two_gpu_unit_test.py."""
    x64 = np.asarray(x, np.float64)
    axes = tuple(range(x64.ndim - 1))
    mean = x64.mean(axes)
    var = x64.var(axes)
    out = (x64 - mean) / np.sqrt(var + eps) * np.asarray(w) + np.asarray(b)
    return out, mean, var


def test_syncbn_matches_whole_batch_oracle(mesh):
    rng = np.random.RandomState(0)
    N, H, W, C = 16, 4, 4, 8
    x = rng.randn(N, H, W, C).astype(np.float32)
    w = rng.rand(C).astype(np.float32) + 0.5
    b = rng.randn(C).astype(np.float32)

    bn = SyncBatchNorm(C, process_group="data")
    params, state = bn.init()
    params = {"weight": jnp.asarray(w), "bias": jnp.asarray(b)}

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P("data"), P(), P(), P(), P()),
        out_specs=(P("data"), P(), P()))
    def run(xs, wt, bs, rm, rv):
        out, new_state = bn.apply({"weight": wt, "bias": bs},
                                  {"running_mean": rm, "running_var": rv}, xs)
        return out, new_state["running_mean"], new_state["running_var"]

    out, new_rm, new_rv = run(jnp.asarray(x), params["weight"], params["bias"],
                              state["running_mean"], state["running_var"])
    ref, mean, var = _bn_oracle(x, w, b)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4)
    # running stats: momentum 0.1, unbiased var (kernel.py:55-58)
    n = N * H * W
    np.testing.assert_allclose(np.asarray(new_rm), 0.1 * mean, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_rv),
                               0.9 + 0.1 * var * n / (n - 1), atol=1e-4)


def test_syncbn_backward_matches_oracle(mesh):
    """Grad through the distributed BN == grad through single-device BN on the
    whole batch (the hand-written backward of kernel.py:97-113 comes out of
    autodiff through psum)."""
    rng = np.random.RandomState(1)
    N, C = 16, 4
    x = rng.randn(N, C).astype(np.float32)
    w = rng.rand(C).astype(np.float32) + 0.5
    b = rng.randn(C).astype(np.float32)

    # the 0.4-era check_rep cannot infer the autodiff-psummed gw/gb
    # replicated (a jax with vma typing can); disable the check there
    from apex_tpu.utils.pallas import has_vma
    has_vma = has_vma()

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P("data"), P(), P()),
        out_specs=(P("data"), P(), P()),
        **({} if has_vma else {"check_vma": False}))
    def dist_grads(xs, wt, bs):
        def f(xs, wt, bs):
            out, _, _ = sync_batch_norm(xs, wt, bs, axis_name="data")
            return jnp.sum(out ** 2)
        # with the replication check on, shard_map autodiff psums
        # cotangents of replicated inputs itself; with it off (the old-jax
        # path above) they come back device-local and need the psum here
        gx, gw, gb = jax.grad(f, argnums=(0, 1, 2))(xs, wt, bs)
        if not has_vma:
            gw = jax.lax.psum(gw, "data")
            gb = jax.lax.psum(gb, "data")
        return gx, gw, gb

    gx, gw, gb = dist_grads(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))

    def whole(xs, wt, bs):
        out, _, _ = sync_batch_norm(xs, wt, bs, axis_name=None)
        return jnp.sum(out ** 2)

    egx, egw, egb = jax.grad(whole, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(gx), np.asarray(egx), atol=2e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(egw), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(egb), rtol=1e-4)


def test_syncbn_group_axis():
    """Group-scoped sync: stats stay inside each mesh group
    (test_groups.py analog)."""
    gmesh = create_grouped_mesh(group_size=4)
    x = np.zeros((8, 2), np.float32)
    x[4:] = 10.0  # second group of devices sees different data

    @functools.partial(shard_map, mesh=gmesh,
                       in_specs=P(("data", "group")), out_specs=P(("data", "group")))
    def run(xs):
        out, _, _ = sync_batch_norm(xs, None, None, axis_name="group")
        return out

    out = np.asarray(run(jnp.asarray(x)))
    # within each group values are identical -> normalized output is 0
    np.testing.assert_allclose(out, 0.0, atol=1e-5)


def test_syncbn_default_syncs_whole_world(mesh):
    """process_group=None (the reference default) syncs over every bound mesh
    axis — regression: the old GROUP_AXIS default crashed under a plain data
    mesh."""
    bn = SyncBatchNorm(2, affine=False, track_running_stats=False)
    x = np.zeros((8, 2), np.float32)
    x[4:] = 10.0

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def run(xs):
        out, _ = bn.apply({}, {}, xs)
        return out

    out = np.asarray(run(jnp.asarray(x)))
    # stats are global: mean 5, so outputs are +-1 after normalize
    np.testing.assert_allclose(np.abs(out), 1.0, rtol=1e-4)


def test_syncbn_eval_without_running_stats():
    """track_running_stats=False in eval falls back to batch statistics
    (torch.nn.BatchNorm semantics) instead of crashing."""
    bn = SyncBatchNorm(2, affine=False, track_running_stats=False)
    x = jnp.asarray(np.random.RandomState(3).randn(8, 2).astype(np.float32))
    out, _ = bn.apply({}, {}, x, training=False)
    np.testing.assert_allclose(np.asarray(out).mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out).std(0), 1.0, atol=1e-2)


def test_syncbn_eval_mode_and_fused_relu():
    x = jnp.asarray(np.linspace(-2, 2, 16, dtype=np.float32).reshape(8, 2))
    rm = jnp.zeros((2,)); rv = jnp.ones((2,))
    out, _, _ = sync_batch_norm(x, None, None, rm, rv, axis_name=None,
                                training=False, fuse_relu=True)
    expected = np.maximum(np.asarray(x), 0.0)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)


def test_syncbn_residual_add():
    x = jnp.ones((4, 3)); z = jnp.full((4, 3), 2.0)
    out, _, _ = sync_batch_norm(x, None, None, axis_name=None, z=z)
    np.testing.assert_allclose(np.asarray(out), 2.0, atol=1e-5)


def test_convert_syncbn_model():
    class BatchNorm:  # stand-in local BN module
        __module__ = "apex_tpu.models.layers"
        def __init__(self, n):
            self.num_features = n; self.eps = 1e-5; self.momentum = 0.1
            self.affine = True; self.track_running_stats = True

    class Block:
        __module__ = "apex_tpu.models.layers"
        def __init__(self):
            self.bn = BatchNorm(8)
            self.sub = [BatchNorm(4), "not_a_module"]

    conv = parallel.convert_syncbn_model(Block())
    assert isinstance(conv.bn, SyncBatchNorm) and conv.bn.num_features == 8
    assert isinstance(conv.sub[0], SyncBatchNorm)
    assert conv.sub[1] == "not_a_module"


# ---------------------------------------------------------------------------
# LARC
# ---------------------------------------------------------------------------

def test_larc_clip_matches_reference_math():
    """One LARC+SGD step vs hand-computed update (LARC.py:84-106)."""
    p = {"w": jnp.asarray([3.0, 4.0])}          # ||p|| = 5
    g = {"w": jnp.asarray([0.6, 0.8])}          # ||g|| = 1
    lr, tc, wd = 0.1, 0.02, 0.01
    opt = LARC(FusedSGD(lr=lr, momentum=0.0, weight_decay=wd),
               trust_coefficient=tc, clip=True)
    state = opt.init(p)
    new_p, _ = opt.step(state, g, p)

    adaptive = tc * 5.0 / (1.0 + 5.0 * wd + 1e-8)
    scale = min(adaptive / lr, 1.0)
    eff_g = (np.asarray([0.6, 0.8]) + wd * np.asarray([3.0, 4.0])) * scale
    expected = np.asarray([3.0, 4.0]) - lr * eff_g
    np.testing.assert_allclose(np.asarray(new_p["w"]), expected, rtol=1e-6)
    # inner wd restored after the step
    assert opt.optim.weight_decay == wd


def test_larc_scale_mode_zero_grad_guard():
    p = {"w": jnp.asarray([1.0, 1.0])}
    g = {"w": jnp.zeros(2)}
    opt = LARC(FusedSGD(lr=0.1, momentum=0.0), clip=False)
    state = opt.init(p)
    new_p, _ = opt.step(state, g, p)
    np.testing.assert_allclose(np.asarray(new_p["w"]), 1.0)


def test_larc_zero_grad_no_weight_decay_leak():
    """Regression: the zero-norm guard must skip the decay fold too — frozen
    params must not decay (reference guard skips the whole block)."""
    p = {"w": jnp.asarray([1.0, 1.0])}
    g = {"w": jnp.zeros(2)}
    opt = LARC(FusedSGD(lr=0.1, momentum=0.0, weight_decay=0.5))
    state = opt.init(p)
    new_p, _ = opt.step(state, g, p)
    np.testing.assert_allclose(np.asarray(new_p["w"]), 1.0)


def test_larc_schedule_lr_alignment():
    """Regression: with a callable lr, LARC clips against the lr the wrapped
    optimizer actually uses this step (count+1), so a 0-at-step-0 warmup
    schedule cannot produce inf/nan."""
    sched = lambda t: 0.1 * jnp.minimum(t / 2.0, 1.0)  # lr(0)=0, lr(1)=0.05
    p = {"w": jnp.asarray([3.0, 4.0])}
    g = {"w": jnp.asarray([0.6, 0.8])}
    opt = LARC(FusedSGD(lr=sched, momentum=0.0))
    state = opt.init(p)
    new_p, _ = opt.step(state, g, p)
    assert np.all(np.isfinite(np.asarray(new_p["w"])))
    # step used lr(1)=0.05; adaptive=0.02*5/1=0.1 => clip ratio 2 -> scale 1
    np.testing.assert_allclose(np.asarray(new_p["w"]),
                               [3.0 - 0.05 * 0.6, 4.0 - 0.05 * 0.8], rtol=1e-6)
