"""Oracle tests for the multi-tensor flat engine + Pallas kernels —
mirrors tests/L0/run_amp/test_multi_tensor_scale.py / _axpby / _l2norm
(fused vs reference numerics + overflow-flag cases), run in Pallas
interpret mode on CPU."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor_apply import (
    TreeFlattener, multi_tensor_scale, multi_tensor_axpby, multi_tensor_l2norm)


def make_tree(key, shapes, dtype=jnp.float32):
    ks = jax.random.split(key, len(shapes))
    return {f"p{i}": jax.random.normal(k, s, dtype)
            for i, (k, s) in enumerate(zip(ks, shapes))}


SHAPES = [(3, 5), (128,), (17, 129), (1,), (64, 64)]


def test_flatten_roundtrip():
    tree = make_tree(jax.random.PRNGKey(0), SHAPES)
    fl = TreeFlattener(tree)
    flat = fl.flatten(tree)
    assert flat.shape[0] % fl.chunk == 0
    out = fl.unflatten(flat)
    for k in tree:
        np.testing.assert_array_equal(out[k], tree[k])


def test_flatten_mixed_dtypes_roundtrip():
    tree = {"a": jnp.ones((5, 7), jnp.bfloat16), "b": jnp.ones((3,), jnp.float32)}
    fl = TreeFlattener(tree)
    out = fl.unflatten(fl.flatten(tree))
    assert out["a"].dtype == jnp.bfloat16
    assert out["b"].dtype == jnp.float32


def test_per_tensor_sumsq():
    tree = make_tree(jax.random.PRNGKey(1), SHAPES)
    fl = TreeFlattener(tree)
    sumsq = fl.per_tensor_sumsq(fl.flatten(tree))
    expect = [float(jnp.sum(tree[f"p{i}"] ** 2)) for i in range(len(SHAPES))]
    np.testing.assert_allclose(np.asarray(sumsq), expect, rtol=1e-5)


def test_multi_tensor_scale():
    tree = make_tree(jax.random.PRNGKey(2), SHAPES)
    fl = TreeFlattener(tree)
    flat = fl.flatten(tree)
    out, flag = multi_tensor_scale(flat, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(flat) * 0.25,
                               rtol=1e-6)
    assert int(flag) == 0


def test_multi_tensor_scale_overflow_flag():
    tree = {"a": jnp.array([1.0, jnp.inf] + [0.0] * 126)}
    fl = TreeFlattener(tree)
    _, flag = multi_tensor_scale(fl.flatten(tree), 1.0)
    assert int(flag) == 1
    tree = {"a": jnp.array([1.0, jnp.nan] + [0.0] * 126)}
    _, flag = multi_tensor_scale(TreeFlattener(tree).flatten(tree), 1.0)
    assert int(flag) == 1


def test_multi_tensor_axpby():
    t1 = make_tree(jax.random.PRNGKey(3), SHAPES)
    t2 = make_tree(jax.random.PRNGKey(4), SHAPES)
    fl = TreeFlattener(t1)
    x, y = fl.flatten(t1), fl.flatten(t2)
    out, flag = multi_tensor_axpby(x, y, 2.0, -0.5)
    np.testing.assert_allclose(np.asarray(out),
                               2.0 * np.asarray(x) - 0.5 * np.asarray(y),
                               rtol=1e-6)
    assert int(flag) == 0


def test_multi_tensor_l2norm():
    tree = make_tree(jax.random.PRNGKey(5), SHAPES)
    fl = TreeFlattener(tree)
    flat = fl.flatten(tree)
    norm = multi_tensor_l2norm(flat)
    np.testing.assert_allclose(float(norm),
                               float(jnp.sqrt(jnp.sum(flat ** 2))), rtol=1e-5)


def test_scale_kernel_jits():
    tree = make_tree(jax.random.PRNGKey(6), [(256,)])
    fl = TreeFlattener(tree)
    f = jax.jit(lambda x: multi_tensor_scale(x, 2.0))
    out, flag = f(fl.flatten(tree))
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(fl.flatten(tree)) * 2.0, rtol=1e-6)
