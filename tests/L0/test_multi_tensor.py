"""Oracle tests for the multi-tensor flat engine + Pallas kernels —
mirrors tests/L0/run_amp/test_multi_tensor_scale.py / _axpby / _l2norm
(fused vs reference numerics + overflow-flag cases), run in Pallas
interpret mode on CPU."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor_apply import (
    TreeFlattener, multi_tensor_scale, multi_tensor_axpby, multi_tensor_l2norm)


def make_tree(key, shapes, dtype=jnp.float32):
    ks = jax.random.split(key, len(shapes))
    return {f"p{i}": jax.random.normal(k, s, dtype)
            for i, (k, s) in enumerate(zip(ks, shapes))}


SHAPES = [(3, 5), (128,), (17, 129), (1,), (64, 64)]


def test_flatten_roundtrip():
    tree = make_tree(jax.random.PRNGKey(0), SHAPES)
    fl = TreeFlattener(tree)
    flat = fl.flatten(tree)
    assert flat.shape[0] % fl.chunk == 0
    out = fl.unflatten(flat)
    for k in tree:
        np.testing.assert_array_equal(out[k], tree[k])


def test_flatten_mixed_dtypes_roundtrip():
    tree = {"a": jnp.ones((5, 7), jnp.bfloat16), "b": jnp.ones((3,), jnp.float32)}
    fl = TreeFlattener(tree)
    out = fl.unflatten(fl.flatten(tree))
    assert out["a"].dtype == jnp.bfloat16
    assert out["b"].dtype == jnp.float32


def test_per_tensor_sumsq():
    tree = make_tree(jax.random.PRNGKey(1), SHAPES)
    fl = TreeFlattener(tree)
    sumsq = fl.per_tensor_sumsq(fl.flatten(tree))
    expect = [float(jnp.sum(tree[f"p{i}"] ** 2)) for i in range(len(SHAPES))]
    np.testing.assert_allclose(np.asarray(sumsq), expect, rtol=1e-5)


def test_multi_tensor_scale():
    tree = make_tree(jax.random.PRNGKey(2), SHAPES)
    fl = TreeFlattener(tree)
    flat = fl.flatten(tree)
    out, flag = multi_tensor_scale(flat, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(flat) * 0.25,
                               rtol=1e-6)
    assert int(flag) == 0


def test_multi_tensor_scale_overflow_flag():
    tree = {"a": jnp.array([1.0, jnp.inf] + [0.0] * 126)}
    fl = TreeFlattener(tree)
    _, flag = multi_tensor_scale(fl.flatten(tree), 1.0)
    assert int(flag) == 1
    tree = {"a": jnp.array([1.0, jnp.nan] + [0.0] * 126)}
    _, flag = multi_tensor_scale(TreeFlattener(tree).flatten(tree), 1.0)
    assert int(flag) == 1


def test_multi_tensor_axpby():
    t1 = make_tree(jax.random.PRNGKey(3), SHAPES)
    t2 = make_tree(jax.random.PRNGKey(4), SHAPES)
    fl = TreeFlattener(t1)
    x, y = fl.flatten(t1), fl.flatten(t2)
    out, flag = multi_tensor_axpby(x, y, 2.0, -0.5)
    np.testing.assert_allclose(np.asarray(out),
                               2.0 * np.asarray(x) - 0.5 * np.asarray(y),
                               rtol=1e-6)
    assert int(flag) == 0


def test_multi_tensor_l2norm():
    tree = make_tree(jax.random.PRNGKey(5), SHAPES)
    fl = TreeFlattener(tree)
    flat = fl.flatten(tree)
    norm = multi_tensor_l2norm(flat)
    np.testing.assert_allclose(float(norm),
                               float(jnp.sqrt(jnp.sum(flat ** 2))), rtol=1e-5)


def test_scale_kernel_jits():
    tree = make_tree(jax.random.PRNGKey(6), [(256,)])
    fl = TreeFlattener(tree)
    f = jax.jit(lambda x: multi_tensor_scale(x, 2.0))
    out, flag = f(fl.flatten(tree))
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(fl.flatten(tree)) * 2.0, rtol=1e-6)


# ---- property tests: randomized pytrees through the flat engine ----------

def _random_tree(rng, depth=0):
    """Random nested dict/list pytree with adversarial leaf shapes: scalars,
    LANE-unaligned vectors, odd matrices, mixed fp32/bf16/fp16."""
    dtypes = [jnp.float32, jnp.bfloat16, jnp.float16]
    shapes = [(), (1,), (7,), (127,), (128,), (129,), (3, 5), (2, 3, 4),
              (64, 33)]

    def leaf():
        shape = shapes[rng.randint(len(shapes))]
        dt = dtypes[rng.randint(len(dtypes))]
        return jnp.asarray(rng.randn(*shape) if shape else rng.randn(),
                           dtype=dt)

    n = rng.randint(2, 5)
    if depth >= 2:
        return {f"l{i}": leaf() for i in range(n)}
    out = {}
    for i in range(n):
        r = rng.rand()
        if r < 0.4:
            out[f"k{i}"] = leaf()
        elif r < 0.6:
            out[f"s{i}"] = [leaf() for _ in range(rng.randint(1, 4))]
        else:
            out[f"d{i}"] = _random_tree(rng, depth + 1)
    return out


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_flatten_roundtrip_random_trees(seed):
    rng = np.random.RandomState(seed)
    tree = _random_tree(rng)
    fl = TreeFlattener(tree)
    flat = fl.flatten(tree)
    assert flat.shape[0] % 128 == 0
    back = fl.unflatten(flat)
    for a, b in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(back)):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_per_tensor_reductions_random_trees(seed):
    rng = np.random.RandomState(100 + seed)
    tree = _random_tree(rng)
    fl = TreeFlattener(tree)
    flat = fl.flatten(tree)
    leaves = [np.asarray(l, np.float32).ravel()
              for l in jax.tree_util.tree_leaves(tree)]
    # bf16/fp16 leaves quantize on pack: compare against the packed values
    packed = [np.asarray(l.astype(fl.dtype), np.float32).ravel()
              for l in jax.tree_util.tree_leaves(tree)]
    want_sumsq = np.array([np.sum(p * p) for p in packed], np.float32)
    got_sumsq = np.asarray(fl.per_tensor_sumsq(flat))
    np.testing.assert_allclose(got_sumsq, want_sumsq, rtol=2e-5, atol=1e-6)
    want_max = np.array([np.max(np.abs(p)) if p.size else 0.0
                         for p in packed], np.float32)
    np.testing.assert_allclose(np.asarray(fl.per_tensor_maxabs(flat)),
                               want_max, rtol=1e-6)
    assert len(leaves) == fl.num_leaves


@pytest.mark.parametrize("seed", [0, 1])
def test_fused_matches_xla_on_random_trees(seed):
    """FusedAdam impl parity on an adversarial pytree: nested structure,
    unaligned shapes (all fp32 — the fused master is fp32 by contract)."""
    from apex_tpu.optimizers import FusedAdam
    rng = np.random.RandomState(200 + seed)
    tree = jax.tree_util.tree_map(
        lambda l: l.astype(jnp.float32), _random_tree(rng))
    grads = jax.tree_util.tree_map(
        lambda l: jnp.asarray(rng.randn(*l.shape), jnp.float32) * 0.1, tree)
    outs = {}
    for impl in ("xla", "fused"):
        opt = FusedAdam(lr=1e-2, weight_decay=0.01, impl=impl)
        state = opt.init(tree)
        p = tree
        for _ in range(3):
            p, state = opt.step(state, grads, p)
        outs[impl] = p
    for a, b in zip(jax.tree_util.tree_leaves(outs["xla"]),
                    jax.tree_util.tree_leaves(outs["fused"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-6, atol=2e-7)
