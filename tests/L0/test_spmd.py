"""GSPMD step engine (ISSUE 12) on the 8-device CPU mesh.

Covers the tentpole and its acceptance gates:

  * THE tp acceptance: a dp=4 x tp=2 ``Plan.apply()`` step trains the
    flagship transformer 6 steps to fp32-tolerance loss vs the dp=8
    baseline, with ``tp.psum`` wire bytes metered and MATCHING the
    compiled-HLO collectives sub-table;
  * sp (ring + ulysses), contrib-ZeRO, and GSPMD-zero1 plans all train
    to the same losses — ``Plan.measurable`` is True across the space;
  * the fused-flat state is genuinely sharded under the GSPMD engine
    (per-device shard = total / flat_world, whole 128-lanes);
  * amp O-level master weights: bf16 model copy over the fp32 master;
  * typed ``SequenceShardingError`` for heads/seq divisibility;
  * the multi-slice DCN alpha-beta terms and the ``@artifact``
    ceilings-calibration hook.
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.models import TransformerConfig
from apex_tpu.parallel import collectives
from apex_tpu.parallel import plan as pm
from apex_tpu.parallel import sequence as seqmod
from apex_tpu.parallel import spmd
from apex_tpu.parallel import weight_update as wu

N_DEV = 8
GB = 8
CFG = pm._flagship_cfg(False)          # the tier-1 flagship stand-in
TINY = TransformerConfig(vocab_size=64, max_len=16, num_layers=1,
                         d_model=32, num_heads=2, d_ff=64,
                         xent_impl="xla")


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {k: os.environ.pop(k, None)
             for k in (collectives.ENV_KNOB, wu.ENV_KNOB,
                       "APEX_TPU_CEILINGS")}
    yield
    for k, v in saved.items():
        os.environ.pop(k, None)
        if v is not None:
            os.environ[k] = v


def _tokens(cfg=CFG, gb=GB, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randint(
        0, cfg.vocab_size, (gb, cfg.max_len)).astype("int32"))


def _run(plan, steps=6, cfg=CFG, gb=GB, meter=False, **kw):
    toks = _tokens(cfg, gb)
    with plan.apply() as mesh:
        carry, step, info = spmd.build_plan_step(
            cfg, mesh, plan, global_batch=gb, meter=meter, **kw)
        losses = []
        for _ in range(steps):
            carry, loss = step(carry, toks)
            losses.append(float(loss))
    return losses, carry, info


@pytest.fixture(scope="module")
def baseline6():
    """The dp=8 all-defaults 6-step loss trajectory every family is
    measured against."""
    losses, _, _ = _run(pm.Plan(dp=N_DEV))
    return losses


def _assert_fp32_tolerance(losses, baseline):
    """fp32-tolerance loss parity: the engines change only collective
    *placement*/reduction order, so per-step losses track within the
    accumulated fp32 reassociation drift (loosest at the late, tiny
    losses)."""
    assert losses[-1] < losses[0]                       # actually trains
    for i, (a, b) in enumerate(zip(losses, baseline)):
        assert abs(a - b) <= max(2e-2 * abs(b), 5e-3), \
            f"step {i}: {a} vs baseline {b}"


# ---------------------------------------------------------------------------
# THE tp acceptance: dp4 x tp2 vs dp8, 6 steps, metered == compiled
# ---------------------------------------------------------------------------

def test_dp4_tp2_trains_to_fp32_tolerance_with_metered_psum(baseline6):
    from apex_tpu import telemetry
    from apex_tpu.telemetry import events as tel_events

    sink = telemetry.MemorySink()
    reg = telemetry.Registry(sink=sink, flush_interval=0,
                             rank0_only=False, run_id="t", memory=False)
    prev = tel_events.set_default(reg)
    try:
        losses, carry, info = _run(pm.Plan(dp=4, tp=2), meter=True)
    finally:
        tel_events.set_default(prev)
    _assert_fp32_tolerance(losses, baseline6)

    # the engine's tp.psum meter must MATCH the compiled-HLO
    # collectives sub-table (same numbers, two independent readers)
    sub = info["collectives"]
    assert "all-reduce" in sub and sub["all-reduce"]["logical_bytes"] > 0
    vals = reg.read()
    assert vals["tp.psum_bytes"] == int(sub["all-reduce"]["logical_bytes"])
    assert vals["tp.psum_compressed_bytes"] == \
        int(sub["all-reduce"]["logical_bytes"])
    assert vals["tp.psum_calls"] == 1      # one meter record per build
    assert info["metered"]["all-reduce"] == sub["all-reduce"]
    # and the summary folds the new family into the collective line
    reg.flush()
    from apex_tpu.telemetry import report as treport
    s = treport.summarize(sink.records)
    assert s["collective_bytes"] >= vals["tp.psum_bytes"]


def test_gspmd_flat_state_is_actually_sharded():
    """The fused-flat master/moment buffers are physically 1/flat_world
    per device, on whole 128-lanes (the chunk-lattice pin)."""
    from apex_tpu.multi_tensor_apply.flattener import LANE
    plan = pm.Plan(dp=4, tp=2, update_sharding="zero1")
    with plan.apply() as mesh:
        carry, step, info = spmd.build_plan_step(
            CFG, mesh, plan, global_batch=GB, meter=False)
        assert info["flat_world"] == 8
        master = carry.master
        total = master.shape[0]
        assert total % (LANE * 8) == 0
        shard_shapes = {s.data.shape for s in
                        master.addressable_shards}
        assert shard_shapes == {(total // 8,)}
        carry, loss = step(carry, _tokens())
        assert {s.data.shape for s in carry.master.addressable_shards} \
            == {(total // 8,)}
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# the other families train to the same losses
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("plan", [
    pm.Plan(dp=4, sp=2, sp_strategy="ring"),
    pm.Plan(dp=2, sp=4, sp_strategy="ulysses"),
    pm.Plan(dp=8, zero=True),
], ids=["sp-ring", "sp-ulysses", "zero"])
def test_family_trains_to_fp32_tolerance(plan, baseline6):
    losses, _, info = _run(plan, steps=6)
    _assert_fp32_tolerance(losses, baseline6)
    assert info["family"] == plan.family


def test_sp_engine_meters_static_schedule():
    """The sp wire is metered from the engine's static schedule (the
    layer scan hides ring/ulysses collectives from the compiled-HLO
    entry walk): ulysses = 8 all_to_alls/layer of one local block,
    ring = 4*n ppermutes/layer."""
    from apex_tpu import telemetry
    from apex_tpu.telemetry import events as tel_events
    sink = telemetry.MemorySink()
    reg = telemetry.Registry(sink=sink, flush_interval=0,
                             rank0_only=False, run_id="t", memory=False)
    prev = tel_events.set_default(reg)
    try:
        _, _, info = _run(pm.Plan(dp=2, sp=4, sp_strategy="ulysses"),
                          steps=1, meter=True)
        _, _, info_r = _run(pm.Plan(dp=4, sp=2, sp_strategy="ring"),
                            steps=1, meter=True)
    finally:
        tel_events.set_default(prev)
    blk = (8 // 2) * CFG.num_heads * (CFG.max_len // 4) \
        * CFG.head_dim * 4
    assert info["sp_wire"]["op"] == "all_to_all"
    assert info["sp_wire"]["logical_bytes"] == \
        8 * CFG.num_layers * blk
    blk_r = (8 // 4) * CFG.num_heads * (CFG.max_len // 2) \
        * CFG.head_dim * 4
    assert info_r["sp_wire"]["logical_bytes"] == \
        4 * CFG.num_layers * 2 * blk_r
    vals = reg.read()
    assert vals["sp.all_to_all_bytes"] == info["sp_wire"]["logical_bytes"]
    assert vals["sp.ppermute_bytes"] == info_r["sp_wire"]["logical_bytes"]


def test_dp4_pp2_trains_to_fp32_tolerance_with_metered_ppermute(baseline6):
    """ACCEPTANCE (ISSUE 17): the GPipe engine (dp=4 x pp=2, M=2)
    trains the flagship 6 steps to fp32-tolerance vs the dp=8
    baseline, with the ``pp.ppermute`` wire metered from the engine's
    exact static schedule (the fori_loop hides the hops from the
    compiled-HLO entry walk, like the sp ring)."""
    from apex_tpu import telemetry
    from apex_tpu.telemetry import events as tel_events
    sink = telemetry.MemorySink()
    reg = telemetry.Registry(sink=sink, flush_interval=0,
                             rank0_only=False, run_id="t", memory=False)
    prev = tel_events.set_default(reg)
    try:
        losses, _, info = _run(
            pm.Plan(dp=4, pp_stages=2, pp_microbatches=2), meter=True)
    finally:
        tel_events.set_default(prev)
    _assert_fp32_tolerance(losses, baseline6)
    assert info["engine"] == "shard_map.pp"
    assert info["stages_layers"] == CFG.num_layers // 2
    assert info["pipeline_bubble_fraction"] == pytest.approx(1 / 3)
    # the static schedule: (M + S - 1) ticks, each hopping one
    # microbatch activation block, and the backward mirrors every hop
    esize = jnp.dtype(CFG.dtype).itemsize
    blk = (GB // 4 // 2) * CFG.max_len * CFG.d_model * esize
    sched = info["pp_wire"]
    assert sched["op"] == "ppermute"
    assert sched["ticks"] == 2 + 2 - 1
    assert sched["per_tick_block_bytes"] == blk
    assert sched["logical_bytes"] == 2 * 3 * blk
    vals = reg.read()
    assert vals["pp.ppermute_bytes"] == sched["logical_bytes"]


def test_dp4_ep2_loss_parity_vs_dp_moe_twin_with_metered_a2a():
    """ACCEPTANCE (ISSUE 17): the switch-MoE engine (dp=4 x ep=2)
    holds per-step loss parity vs the dp-MoE twin — the SAME engine on
    a data-only mesh (full expert set per device, no exchange), the
    identical per-token function — and the compiled ``ep.all_to_all``
    payload equals the static capacity-factored schedule (two
    independent readers of the same wire)."""
    from apex_tpu import telemetry
    from apex_tpu.telemetry import events as tel_events

    def run_twin():
        plan = pm.Plan(dp=N_DEV)
        toks = _tokens()
        with plan.apply() as mesh:
            carry, step, _ = spmd._build_ep_step(
                CFG, mesh, plan, GB, 1e-2, False)
            losses = []
            for _ in range(6):
                carry, loss = step(carry, toks)
                losses.append(float(loss))
        return losses

    sink = telemetry.MemorySink()
    reg = telemetry.Registry(sink=sink, flush_interval=0,
                             rank0_only=False, run_id="t", memory=False)
    prev = tel_events.set_default(reg)
    try:
        losses, _, info = _run(pm.Plan(dp=4, ep=2), meter=True)
    finally:
        tel_events.set_default(prev)
    _assert_fp32_tolerance(losses, run_twin())
    assert info["engine"] == "shard_map.ep"
    assert info["experts"] == pm.EP_DEFAULT_EXPERTS
    a2a = info["metered"]["all-to-all"]
    assert int(a2a["logical_bytes"]) == \
        int(info["ep_wire"]["logical_bytes"])
    vals = reg.read()
    assert vals["ep.all_to_all_bytes"] == int(a2a["logical_bytes"])


def test_amp_bf16_model_copy_over_fp32_master():
    """O2-style master weights through the GSPMD engine: bf16 model
    copy/activations, fp32 master stays authoritative and finite."""
    plan = pm.Plan(dp=4, tp=2)
    with plan.apply() as mesh:
        carry, step, _ = spmd.build_plan_step(
            CFG, mesh, plan, global_batch=GB, meter=False,
            amp_dtype="bfloat16")
        toks = _tokens()
        losses = []
        for _ in range(4):
            carry, loss = step(carry, toks)
            losses.append(float(loss))
    assert carry.master.dtype == jnp.float32
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# plan-space surface: measurable everywhere, engine-aware enumeration
# ---------------------------------------------------------------------------

def test_measurable_true_across_families():
    for plan in (pm.Plan(dp=8), pm.Plan(dp=4, tp=2),
                 pm.Plan(dp=4, sp=2, sp_strategy="ring"),
                 pm.Plan(dp=8, zero=True),
                 pm.Plan(dp=4, tp=2, update_sharding="zero1")):
        assert plan.measurable, plan.describe()
    assert pm.Plan(dp=4, tp=2).family == "tp"
    assert pm.Plan(dp=8, zero=True).family == "zero"
    assert pm.Plan(dp=4, sp=2).family == "sp"
    assert pm.Plan(dp=8).family == "dp"


def test_enumeration_matches_engine_constraints():
    """tp plans carry fp32 wire only (GSPMD owns the collectives) and
    never contrib ZeRO; sp plans drop contrib ZeRO but keep the
    compressed dp wire (their dp reduction is the explicit DDP path)."""
    prof = pm.ModelProfile(
        name="synth", flops=1e9, bytes_accessed=1e8, params_bytes=4096,
        optimizer_bytes=12288, activations_bytes=8192, batch_bytes=1024,
        temps_bytes=512, output_bytes=64, peak_hbm_bytes=30000,
        layers=2, act_layer_bytes=4096, seq=4096, heads=8,
        platform="cpu")
    plans = pm.enumerate_plans(prof, N_DEV, platform="cpu", sp_min_seq=64)
    tp_plans = [p for p in plans if p.tp > 1]
    sp_plans = [p for p in plans if p.sp > 1]
    assert tp_plans and sp_plans
    assert all(p.collective_scheme == "fp32" for p in tp_plans)
    assert not any(p.zero for p in tp_plans)
    assert any(p.update_sharding == "zero1" for p in tp_plans)
    assert not any(p.zero for p in sp_plans)
    assert any(p.collective_scheme == "int8_blockscale" for p in sp_plans)
    assert all(p.measurable for p in plans)


# ---------------------------------------------------------------------------
# typed sequence-sharding errors (satellite)
# ---------------------------------------------------------------------------

def test_ulysses_head_divisibility_typed_error():
    with pytest.raises(seqmod.SequenceShardingError,
                       match=r"num_heads 2 does not divide over sp=4"):
        seqmod.validate_sp(16, 2, 4, "ulysses")
    with pytest.raises(seqmod.SequenceShardingError,
                       match=r"sequence length 15 does not chunk"):
        seqmod.validate_sp(15, 4, 4, "ring")
    seqmod.validate_sp(16, 2, 1, "ulysses")     # sp=1 always fine
    # and through the engine, before anything traces
    plan = pm.Plan(dp=2, sp=4, sp_strategy="ulysses")
    with plan.apply() as mesh:
        with pytest.raises(seqmod.SequenceShardingError,
                           match="num_heads"):
            spmd.build_plan_step(TINY, mesh, plan, global_batch=8,
                                 meter=False)


# ---------------------------------------------------------------------------
# multi-slice DCN terms + ceilings calibration hook
# ---------------------------------------------------------------------------

CEIL = {"peak_flops": 1e12, "peak_bw": 1e11, "ici_bw": 1e10,
        "ici_alpha_s": 1e-6, "hbm_bytes": 1e12,
        "dcn_bw": 1e9, "dcn_alpha_s": 1e-4}


def test_multislice_dcn_terms_oracle():
    """8-way allreduce over 2 slices = intra 4-ring on ICI + inter
    2-ring of 1/4 payload on DCN (hand-computed)."""
    logical = 4 * (1 << 20)
    flat = pm.collective_time_s("all_reduce", logical, 8, CEIL)
    two = pm.collective_time_s("all_reduce", logical, 8, CEIL, slices=2)
    intra = (2 * 3 * CEIL["ici_alpha_s"]
             + 2.0 * 3 / 4 * logical / CEIL["ici_bw"])
    inter = (2 * 1 * CEIL["dcn_alpha_s"]
             + 2.0 * 1 / 2 * (logical / 4) / CEIL["dcn_bw"])
    assert two == pytest.approx(intra + inter)
    assert two > flat          # the slow DCN tier costs more
    # slices that don't divide fall back to the flat model
    assert pm.collective_time_s("all_reduce", logical, 8, CEIL,
                                slices=3) == flat
    # and predict() charges the dp wire its DCN tier
    prof = pm.ModelProfile(
        name="s", flops=1e9, bytes_accessed=1e8, params_bytes=1 << 20,
        optimizer_bytes=3 << 20, activations_bytes=8192,
        batch_bytes=1024, temps_bytes=512, output_bytes=64,
        peak_hbm_bytes=1 << 22, platform="cpu")
    p1 = pm.predict(prof, pm.Plan(dp=8), ceilings=dict(CEIL))
    t1 = p1.breakdown["dp_comm_ms"]
    p2 = pm.predict(prof, pm.Plan(dp=8),
                    ceilings=dict(CEIL, num_slices=2))
    assert p2.breakdown["dp_comm_ms"] > t1


def test_ceilings_calibration_ingests_plan_artifact(tmp_path,
                                                    monkeypatch):
    """APEX_TPU_CEILINGS="@PLAN_AB.json" folds a measured plan leg's
    one-point calibration into the ceilings row (the HW_CEILINGS
    calibration hook)."""
    from apex_tpu.pyprof.prof import resolve_ceilings, calibrate_ceilings
    art = {"metric": "plan_ab", "backend": "tpu",
           "plan": {"leg": "plan", "calibration_scale": 2.0,
                    "family_calibration": {"dp": 2.0, "tp": 4.0},
                    "plans": []}}
    path = tmp_path / "PLAN_AB.json"
    path.write_text(json.dumps(art))
    base = resolve_ceilings("cpu")
    monkeypatch.setenv("APEX_TPU_CEILINGS", f"@{path}")
    cal = resolve_ceilings("cpu")
    assert cal["peak_flops"] == pytest.approx(base["peak_flops"] / 2.0)
    assert cal["ici_alpha_s"] == pytest.approx(base["ici_alpha_s"] * 2.0)
    # family spread: tp measured 2x slower than its dp-calibrated
    # prediction -> the comm tier takes the extra hit
    assert cal["ici_bw"] == pytest.approx(base["ici_bw"] / 2.0 / 2.0)
    # a calibration artifact without a measured leg fails loudly
    with pytest.raises(ValueError, match="calibration"):
        calibrate_ceilings(base, {"nope": 1})
    bad = tmp_path / "missing.json"
    monkeypatch.setenv("APEX_TPU_CEILINGS", f"@{bad}")
    with pytest.raises(ValueError, match="cannot read"):
        resolve_ceilings("cpu")
