"""ASP 2:4 sparsity tests — mirrors the reference's toy-problem and 3-part
checkpoint-continuity scripts (apex/contrib/sparsity/test/)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity import ASP, create_mask, mn_1d_best
from apex_tpu.optimizers import FusedAdam
from apex_tpu import checkpoint


def brute_force_best_mask_row(row):
    """Oracle: per group of 4, keep the 2 largest |values|."""
    out = np.zeros_like(row)
    for g in range(0, len(row), 4):
        grp = np.abs(row[g:g + 4])
        keep = np.argsort(-grp)[:2]
        for k in keep:
            out[g + k] = 1.0
    return out


def test_mn_1d_best_matches_bruteforce():
    rng = np.random.RandomState(0)
    mat = rng.randn(6, 16).astype(np.float32)
    mask = np.asarray(mn_1d_best(jnp.asarray(mat), 4, 2))
    for i in range(mat.shape[0]):
        np.testing.assert_array_equal(mask[i],
                                      brute_force_best_mask_row(mat[i]))


def test_mask_density_and_axis():
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    m_last = create_mask(w, axis=-1)
    assert float(m_last.mean()) == 0.5
    # every aligned group of 4 along the masked axis has exactly 2 kept
    g = np.asarray(m_last).reshape(8, 4, 4).sum(axis=2)
    assert (g == 2).all()
    m_contract = create_mask(w, axis=-2)       # default ASP axis
    gc = np.asarray(m_contract).reshape(2, 4, 16).sum(axis=1)
    assert (gc == 2).all()


def test_create_mask_ragged_pads_prefer_masking():
    w = jnp.asarray(np.arange(1, 7, dtype=np.float32).reshape(1, 6))
    m = np.asarray(create_mask(w, axis=-1))
    # group 2 is ragged (2 real + 2 pad): both real elements kept
    assert m[0, 4] == 1 and m[0, 5] == 1
    assert m.sum() == 4  # 2 + 2


def _toy_params(seed=0):
    k = jax.random.split(jax.random.PRNGKey(seed), 3)
    return {
        "fc1": {"w": jax.random.normal(k[0], (16, 32)),
                "b": jnp.zeros((32,))},
        "fc2": {"w": jax.random.normal(k[1], (32, 8)),
                "b": jnp.zeros((8,))},
        "tiny": jax.random.normal(k[2], (3, 5)),   # ineligible (divisibility)
    }


def test_eligibility_rules():
    asp = ASP(verbosity=0).init_model_for_pruning(_toy_params())
    elig = asp._eligible_paths
    assert "fc1/w" in elig and "fc2/w" in elig
    assert "fc1/b" not in elig          # ndim < 2
    assert "tiny" not in elig           # 5 % 8 != 0, 3 % 4 != 0
    asp2 = ASP(disallowed_layer_names=("fc2",)).init_model_for_pruning(
        _toy_params())
    assert "fc2/w" not in asp2._eligible_paths
    asp3 = ASP(allowed_layer_names=("fc2",)).init_model_for_pruning(
        _toy_params())
    assert asp3._eligible_paths == frozenset({"fc2/w"})


def test_requires_init_ordering():
    asp = ASP()
    with pytest.raises(RuntimeError):
        asp.compute_sparse_masks(_toy_params())


def sparsity_ok(p, masks):
    """Eligible leaves 2:4 along axis -2; ineligible untouched (mask==1)."""
    w = np.asarray(p["fc1"]["w"])
    groups = w.reshape(4, 4, 32)
    nz = (groups != 0).sum(axis=1)
    return (nz <= 2).all()


def test_wrapped_optimizer_keeps_sparsity():
    params = _toy_params()
    asp = ASP().init_model_for_pruning(params)
    masks = asp.compute_sparse_masks(params)
    params = asp.prune(params, masks)
    opt = asp.wrap_optimizer(FusedAdam(lr=1e-2, weight_decay=0.01), masks)
    state = opt.init(params)
    step = jax.jit(lambda s, g, p: opt.step(s, g, p))
    for i in range(4):
        grads = jax.tree_util.tree_map(
            lambda x: 0.1 * jnp.ones_like(x) * (i + 1), params)
        params, state = step(state, grads, params)
    assert sparsity_ok(params, masks)
    # the bias (ineligible) did train
    assert float(jnp.abs(params["fc1"]["b"]).sum()) > 0


def test_checkpoint_continuity():
    """Part-1 train -> save; part-2 load -> recompute masks -> masks equal
    and training continues sparse (the reference's checkpointing_test_part1/
    2 flow)."""
    params = _toy_params()
    asp = ASP().init_model_for_pruning(params)
    masks = asp.compute_sparse_masks(params)
    params = asp.prune(params, masks)
    opt = asp.wrap_optimizer(FusedAdam(lr=1e-2), masks)
    state = opt.init(params)
    for i in range(2):
        grads = jax.tree_util.tree_map(lambda x: 0.1 * jnp.ones_like(x),
                                       params)
        params, state = opt.step(state, grads, params)
    checkpoint.save("/tmp/asp_ckpt.pkl", params=params)

    # "part 2": fresh process state
    loaded = checkpoint.load("/tmp/asp_ckpt.pkl")["params"]
    loaded = checkpoint.restore_like(params, loaded)
    asp2 = ASP().init_model_for_pruning(loaded)
    masks2 = asp2.compute_sparse_masks(loaded)
    # a pruned weight's mask recomputes to itself
    for a, b in zip(jax.tree_util.tree_leaves(masks),
                    jax.tree_util.tree_leaves(masks2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    opt2 = asp2.wrap_optimizer(FusedAdam(lr=1e-2), masks2)
    st2 = opt2.init(loaded)
    p2, _ = opt2.step(st2, jax.tree_util.tree_map(
        lambda x: 0.1 * jnp.ones_like(x), loaded), loaded)
    assert sparsity_ok(p2, masks2)


def test_masks_jit_and_grad_safe():
    params = _toy_params()
    asp = ASP().init_model_for_pruning(params)
    masks = jax.jit(asp.compute_sparse_masks)(params)
    assert float(masks["fc1"]["w"].mean()) == 0.5
