"""amp tests: casting semantics (analog of tests/L0/run_amp/test_basic_casts.py
driven by ALWAYS_HALF/ALWAYS_BFLOAT16/ALWAYS_FLOAT expectation tables),
promotion (test_promotion.py), opt-level properties, end-to-end toy training
with dynamic scaling and overflow skip (test_fused_sgd/test_checkpointing
spirit)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import apex_tpu
from apex_tpu import amp
from apex_tpu.amp import amp as amp_mod
from apex_tpu.amp import scaler as sc
from apex_tpu.optimizers import FusedAdam, FusedSGD


# --- casting semantics (expectation-table style) ---------------------------

@pytest.mark.parametrize("ptype", [jnp.float16, jnp.bfloat16])
def test_autocast_matmul_low_precision(ptype):
    with amp_mod.autocast(ptype):
        x = jnp.ones((8, 8), jnp.float32)
        y = jnp.ones((8, 8), jnp.float32)
        out = jnp.matmul(x, y)
    assert out.dtype == ptype   # ALWAYS_HALF / ALWAYS_BFLOAT16


@pytest.mark.parametrize("ptype", [jnp.float16, jnp.bfloat16])
def test_autocast_fp32_funcs(ptype):
    with amp_mod.autocast(ptype):
        x = jnp.ones((8, 8), ptype)
        out = jnp.exp(x)
        s = jnp.sum(x)
    assert out.dtype == jnp.float32   # ALWAYS_FLOAT
    assert s.dtype == jnp.float32


def test_autocast_under_jit():
    """Casts must be baked into traced graphs."""
    with amp_mod.autocast(jnp.bfloat16):
        f = jax.jit(lambda a, b: jnp.matmul(a, b))
        out = f(jnp.ones((4, 4)), jnp.ones((4, 4)))
    assert out.dtype == jnp.bfloat16
    # patches removed, but the traced fn keeps its casts
    out2 = f(jnp.ones((4, 4)), jnp.ones((4, 4)))
    assert out2.dtype == jnp.bfloat16


def test_promotion_widest_type():
    with amp_mod.autocast(jnp.bfloat16):
        a = jnp.ones((4,), jnp.bfloat16)
        b = jnp.ones((4,), jnp.float32)
        out = jnp.add(a, b)
        cat = jnp.concatenate([a, b])
    assert out.dtype == jnp.float32     # widest wins (test_promotion.py:60)
    assert cat.dtype == jnp.float32     # SEQUENCE_CASTS


def test_autocast_restores_cleanly():
    orig = jnp.matmul
    with amp_mod.autocast(jnp.bfloat16):
        assert jnp.matmul is not orig
    assert jnp.matmul is orig
    out = jnp.matmul(jnp.ones((2, 2)), jnp.ones((2, 2)))
    assert out.dtype == jnp.float32


def test_decorators():
    @amp.half_function
    def f(x):
        return x * 2

    @amp.float_function
    def g(x):
        return x * 3

    with amp_mod.autocast(jnp.bfloat16):
        assert f(jnp.ones((4,), jnp.float32)).dtype == jnp.bfloat16
        assert g(jnp.ones((4,), jnp.bfloat16)).dtype == jnp.float32
    # no-ops when amp is off
    assert f(jnp.ones((4,), jnp.float32)).dtype == jnp.float32


# --- opt-level properties ----------------------------------------------------

def test_opt_level_table():
    from apex_tpu.amp.properties import opt_levels, Properties
    p = opt_levels["O2"](Properties())
    assert p.cast_model_type == jnp.float16
    assert p.master_weights and p.keep_batchnorm_fp32
    assert p.loss_scale == "dynamic"
    p = opt_levels["O4"](Properties())
    assert p.patch_functions_type == jnp.bfloat16
    assert p.loss_scale == 1.0       # bf16 needs no scaling
    p = opt_levels["O5"](Properties())
    assert p.cast_model_type == jnp.bfloat16
    assert p.master_weights
    assert p.loss_scale == 1.0


def test_initialize_o5_casts_and_masters():
    params = {"dense": {"kernel": jnp.ones((8, 8)), "bias": jnp.zeros((8,))},
              "batch_norm": {"scale": jnp.ones((8,)), "bias": jnp.zeros((8,))}}
    st = amp.initialize(params, opt_level="O5", verbosity=0)
    assert st.model_params["dense"]["kernel"].dtype == jnp.bfloat16
    # keep_batchnorm_fp32 honored via path predicate
    assert st.model_params["batch_norm"]["scale"].dtype == jnp.float32
    assert st.master_params["dense"]["kernel"].dtype == jnp.float32
    amp_mod.uninit()


def test_initialize_bad_opt_level():
    with pytest.raises(RuntimeError):
        amp.initialize({}, opt_level="O9")


def test_initialize_flash_attn_backward_knob():
    """The amp-level flash_attn_backward option validates and lands in the
    flash module's process default, where backward="auto" resolution picks
    it up (between the env override and the tuning profile)."""
    from apex_tpu.contrib.multihead_attn import flash as F
    params = {"w": jnp.ones((4, 4))}
    try:
        st = amp.initialize(params, opt_level="O0", verbosity=0,
                            flash_attn_backward="xla")
        assert st.properties.flash_attn_backward == "xla"
        assert F._resolve_backward("auto") == "xla"
        # default initialize resets the process default to auto
        st = amp.initialize(params, opt_level="O0", verbosity=0)
        assert st.properties.flash_attn_backward == "auto"
        assert F._DEFAULT_BACKWARD == "auto"
    finally:
        F.set_default_backward("auto")
    with pytest.raises(ValueError):
        amp.initialize(params, opt_level="O0", verbosity=0,
                       flash_attn_backward="cuda")


# --- end-to-end toy training -------------------------------------------------

def _toy_loss(params, x, y):
    h = jnp.maximum(jnp.dot(x, params["w1"]) + params["b1"], 0.0)
    pred = jnp.dot(h, params["w2"]) + params["b2"]
    return jnp.mean((pred.astype(jnp.float32) - y) ** 2)


def _toy_params(key):
    k1, k2 = jax.random.split(key)
    return {"w1": jax.random.normal(k1, (16, 32)) * 0.1,
            "b1": jnp.zeros((32,)),
            "w2": jax.random.normal(k2, (32, 4)) * 0.1,
            "b2": jnp.zeros((4,))}


@pytest.mark.parametrize("opt_level", ["O0", "O2", "O3", "O5"])
def test_end_to_end_training(opt_level):
    params = _toy_params(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    st = amp.initialize(params, opt, opt_level=opt_level, verbosity=0)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
    y = jax.random.normal(jax.random.PRNGKey(2), (64, 4))

    @jax.jit
    def train_step(st, x, y):
        def scaled_loss_fn(mp):
            loss = _toy_loss(mp, st.cast_input(x), y)
            return amp.scale_loss(loss, st)
        grads = jax.grad(scaled_loss_fn)(st.model_params)
        return amp.frontend.amp_step(st, grads)

    loss0 = _toy_loss(st.params_for_eval(), x, y)
    for _ in range(20):
        st = train_step(st, x, y)
    loss1 = _toy_loss(st.params_for_eval(), x, y)
    assert float(loss1) < float(loss0), (loss0, loss1)
    amp_mod.uninit()


def test_overflow_skips_step_and_halves_scale():
    params = _toy_params(jax.random.PRNGKey(0))
    opt = FusedSGD(lr=0.1, momentum=0.9)
    st = amp.initialize(params, opt, opt_level="O2", verbosity=0)
    bad_grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, jnp.inf, p.dtype), st.model_params)
    scale_before = float(st.loss_scale)
    st2 = amp.frontend.amp_step(st, bad_grads)
    # params unchanged, scale halved
    for k in st.master_params:
        np.testing.assert_array_equal(np.asarray(st2.master_params[k]),
                                      np.asarray(st.master_params[k]))
    assert float(st2.loss_scale) == scale_before / 2


def test_amp_state_dict_roundtrip():
    params = _toy_params(jax.random.PRNGKey(0))
    st = amp.initialize(params, opt_level="O2", num_losses=3, verbosity=0)
    st = st._replace(scalers=tuple(
        sc.update(s, jnp.asarray(False)) for s in st.scalers))
    d = amp.state_dict(st)
    assert len(d) == 3
    st2 = amp.initialize(params, opt_level="O2", num_losses=3, verbosity=0)
    st2 = amp.load_state_dict(st2, d)
    for a, b in zip(st.scalers, st2.scalers):
        assert float(a.loss_scale) == float(b.loss_scale)


def test_multiple_losses_independent_scalers():
    """test_multiple_models_optimizers_losses.py analog: per-loss_id scalers
    evolve independently."""
    params = _toy_params(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-3)
    st = amp.initialize(params, opt, opt_level="O2", num_losses=2, verbosity=0)
    good = jax.tree_util.tree_map(jnp.ones_like, st.model_params)
    bad = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, jnp.nan, p.dtype), st.model_params)
    st = amp.frontend.amp_step(st, good, loss_id=0)
    st = amp.frontend.amp_step(st, bad, loss_id=1)
    assert float(st.scalers[0].loss_scale) == 2.0 ** 16
    assert float(st.scalers[1].loss_scale) == 2.0 ** 15


# -- legacy handle API (handle.py:170-252, opt.py:9-103) ---------------------

def test_legacy_amp_handle_flow():
    from apex_tpu.amp import init_handle, NoOpHandle
    import numpy as np

    h = init_handle(loss_scale="dynamic")
    s0 = h.loss_scale
    loss = jnp.float32(2.0)
    assert float(h.scale_loss(loss)) == 2.0 * s0
    g = {"w": jnp.ones((4,)) * s0}
    g32, skip = h.unscale_and_update(g)
    assert not skip
    np.testing.assert_allclose(np.asarray(g32["w"]), 1.0)
    # overflow path: halve + skip
    bad = {"w": jnp.full((4,), jnp.inf)}
    _, skip = h.unscale_and_update(bad)
    assert skip and h.loss_scale == s0 / 2
    # state dict round trip
    h2 = init_handle()
    h2.load_state_dict(h.state_dict())
    assert h2.loss_scale == h.loss_scale

    # disabled -> NoOpHandle passthrough
    nh = init_handle(enabled=False)
    assert isinstance(nh, NoOpHandle)
    assert float(nh.scale_loss(loss)) == 2.0
    _, skip = nh.unscale_and_update(bad)
    assert not skip


def test_legacy_optim_wrapper_multi_loss():
    from apex_tpu.amp import init_handle
    from apex_tpu.optimizers import FusedSGD
    import numpy as np

    h = init_handle()
    opt = h.wrap_optimizer(FusedSGD(lr=0.1), num_loss=2)
    with pytest.raises(RuntimeError):
        h.scale_loss(jnp.float32(1.0))   # must go through the wrapper now
    s0, s1 = opt.loss_scale(0), opt.loss_scale(1)
    g0, skip0 = opt.unscale_and_update({"w": jnp.ones((4,)) * s0}, 0)
    g1, skip1 = opt.unscale_and_update(
        {"w": jnp.full((4,), jnp.inf)}, 1)
    assert not skip0 and skip1
    assert opt.loss_scale(1) == s1 / 2 and opt.loss_scale(0) >= s0
    # attribute passthrough to the wrapped optimizer
    assert opt.lr == 0.1


def test_incoming_params_must_be_fp32():
    """check_params_fp32 analog (_initialize.py:79-116): non-fp32 incoming
    params are rejected unless allow_incoming_model_not_fp32=True."""
    import pytest
    from apex_tpu.optimizers import FusedSGD
    half = {"w": jnp.ones((4, 4), jnp.bfloat16)}
    with pytest.raises(RuntimeError, match="not fp32"):
        amp.initialize(half, FusedSGD(lr=0.1), opt_level="O0", verbosity=0)
    st = amp.initialize(half, FusedSGD(lr=0.1), opt_level="O0", verbosity=0,
                        allow_incoming_model_not_fp32=True)
    # O0's preset then applies its own cast_model_type=fp32, as in the
    # reference (frontend.py O0 preset) — the hatch only skips the check
    assert st.model_params["w"].dtype == jnp.float32
    # integer leaves (e.g. step counters riding the tree) never trigger it
    mixed = {"w": jnp.ones((4, 4), jnp.float32), "steps": jnp.zeros((), jnp.int32)}
    amp.initialize(mixed, FusedSGD(lr=0.1), opt_level="O0", verbosity=0)


def test_cast_model_outputs():
    """cast_model_outputs kwarg (reference frontend.py:269, the forward
    patch's output_caster _initialize.py:185-190): floating outputs cast,
    non-floating untouched, default is a no-op; survives add_param_group."""
    p = {"w": jnp.ones((4, 4))}
    st = amp.initialize(p, FusedSGD(lr=0.1), opt_level="O5", verbosity=0,
                        cast_model_outputs=jnp.float32)
    out = {"logits": jnp.ones((2,), jnp.bfloat16),
           "ids": jnp.zeros((2,), jnp.int32), "aux_loss": 0.5}
    cast = st.cast_output(out)
    assert cast["logits"].dtype == jnp.float32
    assert cast["ids"].dtype == jnp.int32
    assert cast["aux_loss"] == 0.5          # python scalars pass through
    st2 = amp.add_param_group(st, {"w2": jnp.ones((2, 2))})
    assert st2.cast_model_outputs == jnp.float32
    # default: no-op
    st3 = amp.initialize(p, FusedSGD(lr=0.1), opt_level="O5", verbosity=0)
    assert st3.cast_output(out)["logits"].dtype == jnp.bfloat16


def test_initialize_list_of_models():
    """Reference list API (frontend.py:296-331 +
    test_multiple_models_optimizers_losses.py): lists of models AND
    optimizers return a list of independent AmpStates; list params with a
    single optimizer stay a single-model pytree."""
    mA = {"w": jnp.ones((4, 4))}
    mB = {"w": jnp.ones((2, 2)), "b": jnp.zeros((2,))}
    states = amp.initialize([mA, mB], [FusedAdam(lr=1e-3), FusedSGD(lr=0.1)],
                            opt_level="O2", verbosity=0)
    assert isinstance(states, list) and len(states) == 2
    assert states[0].model_params["w"].dtype == jnp.float16
    assert states[1].master_params["b"].dtype == jnp.float32
    # independent scalers
    bad = jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, jnp.inf), states[0].master_params)
    s0 = amp.amp_step(states[0], bad)
    assert float(s0.scalers[0].loss_scale) == 2.0 ** 15
    assert float(states[1].scalers[0].loss_scale) == 2.0 ** 16

    with pytest.raises(ValueError, match="models but"):
        amp.initialize([mA, mB], [FusedAdam(lr=1e-3)], opt_level="O2",
                       verbosity=0)

    # a list pytree with ONE optimizer is a single model
    st = amp.initialize([{"w": jnp.ones((2, 2))}], FusedAdam(lr=1e-3),
                        opt_level="O0", verbosity=0)
    assert not isinstance(st, list)
