"""Weight-update sharding for plain DDP (ISSUE 8) on the 8-device CPU
mesh.

Covers the tentpole and its acceptance gates:

  * knob resolution (``update_sharding`` arg > ``APEX_TPU_UPDATE_SHARDING``
    env > tuning > off) and the ``DistributedDataParallel.weight_update``
    factory returning None when off;
  * THE A/B: the flagship transformer trained N steps with
    ``update_sharding="zero1"`` is BITWISE-identical to the unsharded
    fp32 DDP run (allreduce + replicated fused step + amp-style
    overflow select) when the allgather is fp32, while the NEW
    ``ddp.reduce_scatter``/``ddp.param_allgather`` meters carry the
    expected logical/wire bytes and the
    ``ddp.opt_state_bytes_per_replica`` gauge proves the ~1/N
    optimizer-state shrink;
  * int8_blockscale param allgather: >=3.5x wire compression from the
    counters at tolerance-level loss;
  * amp overflow-skip semantics: a non-finite grad on ONE replica skips
    the step on ALL replicas (the flag is computed pre-scatter), even
    under a quantized reduce-scatter;
  * the sharded per-optimizer paths: elementwise (Adam/SGD/Adagrad via
    the default ``step_flat_shard``) and cross-shard (LAMB/NovoGrad
    overrides) match their unsharded flat trajectories;
  * resilience: ``collective_fail`` chaos fires through the new
    ``ddp.reduce_scatter``/``ddp.param_allgather`` entry points, and a
    TrainGuard preempt/resume mid-run with the SHARDED optimizer state
    (+ error-feedback residual) in the step carry is bitwise-identical
    to an uninterrupted run;
  * the disabled path (``update_sharding="off"``) is bitwise-identical
    to a knob-less DDP;
  * telemetry.memory: sharded ``.m``/``.v`` state slices classify as
    optimizer and ``memory_model`` reports per-replica optimizer bytes.
"""
import functools
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers import (FusedAdam, FusedAdagrad, FusedLAMB,
                                 FusedNovoGrad, FusedSGD)
from apex_tpu.parallel import (DistributedDataParallel, Reducer,
                               collectives, create_mesh)
from apex_tpu.parallel import weight_update as wu
from apex_tpu.parallel.mesh import shard_map
from apex_tpu.resilience import faults
from apex_tpu.telemetry import MemorySink, Registry, events
from apex_tpu.telemetry import records_violations
from apex_tpu.utils.pallas import has_vma, _to_varying

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    return create_mesh({"data": N_DEV})


@pytest.fixture(autouse=True)
def _clean_hooks():
    """No leaked default registry, fault plan, or env knobs between
    tests."""
    prev_reg = events.set_default(None)
    prev_plan = faults.install(None)
    saved = {k: os.environ.pop(k, None)
             for k in (collectives.ENV_KNOB, wu.ENV_KNOB)}
    yield
    events.set_default(prev_reg)
    faults.install(prev_plan)
    for k, v in saved.items():
        os.environ.pop(k, None)
        if v is not None:
            os.environ[k] = v


# ---------------------------------------------------------------------------
# knob resolution / construction guards
# ---------------------------------------------------------------------------

def test_resolve_mode_precedence():
    assert wu.resolve_mode() == "off"            # no env, no tuning (CPU)
    os.environ[wu.ENV_KNOB] = "zero1"
    assert wu.resolve_mode() == "zero1"
    assert wu.resolve_mode("off") == "off"       # explicit beats env
    os.environ[wu.ENV_KNOB] = "bogus"
    with pytest.raises(ValueError, match="update_sharding"):
        wu.resolve_mode()
    with pytest.raises(ValueError, match="update_sharding"):
        wu.resolve_mode("zero2")


def test_construction_guards():
    with pytest.raises(ValueError, match="impl='fused'"):
        wu.ShardedUpdate(FusedAdam(lr=1e-3, impl="xla"))
    with pytest.raises(ValueError, match="update_sharding"):
        DistributedDataParallel(update_sharding="zero3")
    with pytest.raises(ValueError, match="update_sharding"):
        Reducer(update_sharding="zero3")


def test_ddp_factory_off_returns_none_and_allreduce_unchanged(mesh):
    """The disabled path: weight_update() is None and the allreduce
    route is BITWISE what a knob-less DDP produces (the knob being off
    must be indistinguishable from the knob not existing)."""
    ddp_off = DistributedDataParallel(axis_name="data",
                                      update_sharding="off")
    ddp_legacy = DistributedDataParallel(axis_name="data")
    assert ddp_off.weight_update(FusedAdam(impl="fused")) is None
    assert ddp_legacy.weight_update(FusedAdam(impl="fused")) is None
    assert Reducer(axis_name="data").weight_update(
        FusedAdam(impl="fused")) is None

    def run(ddp):
        @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"))
        def red(x):
            return ddp.allreduce_grads({"w": x})["w"]
        rng = np.random.RandomState(0)
        return np.asarray(red(jnp.asarray(
            rng.randn(N_DEV, 256).astype(np.float32))))

    np.testing.assert_array_equal(run(ddp_off), run(ddp_legacy))

    # env opt-in flips the factory on
    os.environ[wu.ENV_KNOB] = "zero1"
    eng = ddp_legacy.weight_update(FusedAdam(impl="fused"))
    assert isinstance(eng, wu.ShardedUpdate)
    assert Reducer(axis_name="data").weight_update(
        FusedAdam(impl="fused")) is not None


# ---------------------------------------------------------------------------
# synthetic flat-buffer fixtures
# ---------------------------------------------------------------------------

def _flat_params():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return {"w": 0.3 * jax.random.normal(k1, (33, 7)),
            "b": 0.1 * jax.random.normal(k2, (130,))}


def _flat_grads(i, poison=False):
    ks = jax.random.split(jax.random.PRNGKey(100 + i), 2)
    g = {"w": jax.random.normal(ks[0], (N_DEV, 33, 7)),
         "b": jax.random.normal(ks[1], (N_DEV, 130))}
    if poison:
        g = jax.tree_util.tree_map(lambda x: x.at[0].set(jnp.inf), g)
    return g


def _make_steps(mesh, opt_unsharded, sharded_update, params):
    """(jitted unsharded amp-style step, jitted sharded step, jitted
    sharded init).  The unsharded baseline is today's DDP contract:
    per-leaf allreduce, full replicated ``step_flat``, amp's
    skip-on-overflow select."""
    ddp = DistributedDataParallel(axis_name="data")
    vma_kw = {} if has_vma() else {"check_vma": False}
    pspec = jax.tree_util.tree_map(lambda _: P(), params)
    gspec = jax.tree_util.tree_map(lambda _: P("data"), params)
    state_u = opt_unsharded.init(params)
    uspec = jax.tree_util.tree_map(lambda _: P(), state_u)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(uspec, gspec, pspec),
                       out_specs=(pspec, uspec), **vma_kw)
    def step_u(state, g, p):
        g = jax.tree_util.tree_map(lambda x: x[0], g)
        g = ddp.allreduce_grads(g)
        fl = opt_unsharded.flattener_for(p)
        flat = fl.flatten(g)
        ok = jnp.all(jnp.isfinite(flat)).astype(jnp.float32)
        new_state = opt_unsharded.step_flat(state, flat)
        new_state = jax.tree_util.tree_map(
            lambda nw, old: jnp.where(ok > 0, nw, old), new_state, state)
        return fl.unflatten(new_state.master, like=p), new_state

    sspec = sharded_update.state_pspecs(params, N_DEV)

    @functools.partial(shard_map, mesh=mesh, in_specs=(pspec,),
                       out_specs=sspec)
    def init_s(p):
        return sharded_update.init(p)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(sspec, gspec, pspec),
                       out_specs=(pspec, sspec), **vma_kw)
    def step_s(state, g, p):
        g = jax.tree_util.tree_map(lambda x: x[0], g)
        return sharded_update.step(state, g, p)

    return jax.jit(step_u), jax.jit(step_s), jax.jit(init_s), state_u


@pytest.mark.parametrize("opt_cls", [
    FusedAdam, functools.partial(FusedSGD, momentum=0.9), FusedAdagrad,
    FusedLAMB, FusedNovoGrad,
], ids=["adam", "sgd", "adagrad", "lamb", "novograd"])
def test_sharded_matches_unsharded_flat(mesh, opt_cls):
    """Every fused optimizer's sharded path (default elementwise or the
    LAMB/NovoGrad cross-shard overrides) tracks its unsharded flat
    trajectory.  Elementwise optimizers are exact 1/N decompositions;
    LAMB/NovoGrad re-derive their cross-tensor norms via psum'd partials
    (different reduction order than the static row-range/Pallas kernels
    — tolerance-level, not bitwise)."""
    params = _flat_params()
    opt_u = opt_cls(lr=1e-2, weight_decay=0.01, impl="fused")
    su = wu.ShardedUpdate(opt_cls(lr=1e-2, weight_decay=0.01,
                                  impl="fused"), axis_name="data")
    step_u, step_s, init_s, state_u = _make_steps(mesh, opt_u, su, params)
    state_s = init_s(params)
    pu = ps = params
    for i in range(4):
        g = _flat_grads(i)
        pu, state_u = step_u(state_u, g, pu)
        ps, state_s = step_s(state_s, g, ps)
    for k in params:
        np.testing.assert_allclose(np.asarray(pu[k]), np.asarray(ps[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    assert int(state_s.count) == 4


def test_sharded_adam_bitwise_and_state_shrink(mesh):
    """Elementwise sharding is an EXACT decomposition: fp32 allgather
    Adam is bitwise the unsharded run, and the per-replica sharded state
    holds ~1/N of the unsharded optimizer-state bytes (asserted from
    live shard shapes AND the new gauge)."""
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)
    params = _flat_params()
    opt_u = FusedAdam(lr=1e-2, weight_decay=0.01, impl="fused")
    su = wu.ShardedUpdate(FusedAdam(lr=1e-2, weight_decay=0.01,
                                    impl="fused"), axis_name="data")
    step_u, step_s, init_s, state_u = _make_steps(mesh, opt_u, su, params)
    state_s = init_s(params)
    pu = ps = params
    for i in range(6):
        g = _flat_grads(i)
        pu, state_u = step_u(state_u, g, pu)
        ps, state_s = step_s(state_s, g, ps)
    for k in params:
        np.testing.assert_array_equal(np.asarray(pu[k]),
                                      np.asarray(ps[k]), err_msg=k)

    # per-replica state: each flat field holds total/N elements
    fl = su._fl(params, N_DEV)
    assert state_s.master.addressable_shards[0].data.shape == \
        (fl.total // N_DEV,)
    unsharded_bytes = sum(
        l.size * jnp.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(state_u))
    vals = reg.read()
    per_replica = vals["ddp.opt_state_bytes_per_replica"]
    assert vals["ddp.update_shard_world"] == N_DEV
    # note the unsharded baseline pads to DEFAULT_CHUNK; compare against
    # the same layout's bytes: 3 flat fields of fl.total on 1 replica
    full_flat_bytes = 3 * fl.total * 4 + 4
    assert per_replica == pytest.approx(full_flat_bytes / N_DEV, rel=0.05)
    assert unsharded_bytes >= full_flat_bytes  # default chunk pads larger


def test_gradient_predivide_factor_matches_unsharded(mesh):
    """The reference predivide semantics (divide by f before the
    reduce, multiply back f/world after) thread through the sharded
    path — DDP's knob must not go inert under update_sharding."""
    params = _flat_params()
    ddp = DistributedDataParallel(axis_name="data",
                                  gradient_predivide_factor=4.0,
                                  update_sharding="zero1")
    opt_u = FusedAdam(lr=1e-2, impl="fused")
    su = ddp.weight_update(FusedAdam(lr=1e-2, impl="fused"))
    assert su.gradient_predivide_factor == 4.0
    vma_kw = {} if has_vma() else {"check_vma": False}
    pspec = jax.tree_util.tree_map(lambda _: P(), params)
    gspec = jax.tree_util.tree_map(lambda _: P("data"), params)
    state_u = opt_u.init(params)
    uspec = jax.tree_util.tree_map(lambda _: P(), state_u)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(uspec, gspec, pspec),
                       out_specs=(pspec, uspec), **vma_kw)
    def step_u(state, g, p):
        g = jax.tree_util.tree_map(lambda x: x[0], g)
        g = ddp.allreduce_grads(g)        # carries the predivide knob
        fl = opt_u.flattener_for(p)
        flat = fl.flatten(g)
        ok = jnp.all(jnp.isfinite(flat)).astype(jnp.float32)
        new_state = opt_u.step_flat(state, flat)
        new_state = jax.tree_util.tree_map(
            lambda nw, old: jnp.where(ok > 0, nw, old), new_state, state)
        return fl.unflatten(new_state.master, like=p), new_state

    sspec = su.state_pspecs(params, N_DEV)

    @functools.partial(shard_map, mesh=mesh, in_specs=(pspec,),
                       out_specs=sspec)
    def init_s(p):
        return su.init(p)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(sspec, gspec, pspec),
                       out_specs=(pspec, sspec), **vma_kw)
    def step_s(state, g, p):
        g = jax.tree_util.tree_map(lambda x: x[0], g)
        return su.step(state, g, p)

    step_u = jax.jit(step_u)
    step_s = jax.jit(step_s)
    state_s = jax.jit(init_s)(params)
    pu = ps = params
    for i in range(3):
        g = _flat_grads(i)
        pu, state_u = step_u(state_u, g, pu)
        ps, state_s = step_s(state_s, g, ps)
    for k in params:
        np.testing.assert_array_equal(np.asarray(pu[k]),
                                      np.asarray(ps[k]), err_msg=k)


# ---------------------------------------------------------------------------
# amp overflow-skip: pre-scatter flag, all replicas skip identically
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("scheme", [None, "int8_blockscale"])
def test_overflow_skips_all_replicas(mesh, scheme):
    """An inf in ONE replica's local grads skips the update on ALL
    replicas — bitwise no-op state and params.  With the int8 scatter
    the flag MUST come pre-scatter (quantizing an inf block destroys
    the evidence), which is exactly what the implementation does."""
    params = _flat_params()
    su = wu.ShardedUpdate(FusedAdam(lr=1e-2, impl="fused"),
                          axis_name="data", collective_scheme=scheme)
    _, step_s, init_s, _ = _make_steps(
        mesh, FusedAdam(lr=1e-2, impl="fused"), su, params)
    state0 = init_s(params)
    m0 = np.asarray(state0.master)
    p1, state1 = step_s(state0, _flat_grads(0, poison=True), params)
    assert int(state1.count) == 0              # skipped step not counted
    np.testing.assert_array_equal(np.asarray(state1.master), m0)
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(p1[k], np.float32),
            np.asarray(params[k], np.float32), err_msg=k)
    # and a clean step afterwards applies
    p2, state2 = step_s(state1, _flat_grads(1), params)
    assert int(state2.count) == 1
    assert np.abs(np.asarray(state2.master) - m0).max() > 0


def test_overflow_reverts_residual(mesh):
    """A skipped step must also revert the error-feedback residual (its
    quantization error was never applied) — the ZeRO/PR-7 contract."""
    params = _flat_params()
    su = wu.ShardedUpdate(FusedAdam(lr=1e-2, impl="fused"),
                          axis_name="data",
                          collective_scheme="int8_blockscale:min_bytes=0")
    vma_kw = {} if has_vma() else {"check_vma": False}
    pspec = jax.tree_util.tree_map(lambda _: P(), params)
    gspec = jax.tree_util.tree_map(lambda _: P("data"), params)
    sspec = su.state_pspecs(params, N_DEV)

    @functools.partial(shard_map, mesh=mesh, in_specs=(pspec,),
                       out_specs=(sspec, P("data")))
    def init_s(p):
        return su.init(p), su.init_residual(p)[None]

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(sspec, gspec, pspec, P("data")),
                       out_specs=(pspec, sspec, P("data")), **vma_kw)
    def step_s(state, g, p, res):
        g = jax.tree_util.tree_map(lambda x: x[0], g)
        p2, s2, r2 = su.step(state, g, p, residual=res[0])
        return p2, s2, r2[None]

    state, res = jax.jit(init_s)(params)
    step = jax.jit(step_s)
    _, state1, res1 = step(state, _flat_grads(0), params, res)
    assert float(jnp.abs(res1).max()) > 0          # EF residual is live
    _, state2, res2 = step(state1, _flat_grads(1, poison=True), params,
                           res1)
    assert int(state2.count) == 1
    np.testing.assert_array_equal(np.asarray(res2), np.asarray(res1))
    np.testing.assert_array_equal(np.asarray(state2.master),
                                  np.asarray(state1.master))


# ---------------------------------------------------------------------------
# chaos: collective_fail through the new entry points
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw", [
    {"collective_scheme": "int8_blockscale:min_bytes=0"},
    {"allgather_scheme": "int8_blockscale"},
], ids=["reduce_scatter", "param_allgather"])
def test_collective_fail_fires_through_sharded_paths(mesh, kw):
    faults.install(faults.parse("collective_fail@0"))
    params = _flat_params()
    su = wu.ShardedUpdate(FusedAdam(lr=1e-2, impl="fused"),
                          axis_name="data", **kw)
    _, step_s, init_s, _ = _make_steps(
        mesh, FusedAdam(lr=1e-2, impl="fused"), su, params)
    state = init_s(params)
    with pytest.raises(faults.CollectiveFault):
        step_s(state, _flat_grads(0), params)
    # the fault is consumed: the replay traces clean
    faults.install(None)
    p1, _ = step_s(state, _flat_grads(0), params)
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(p1))


# ---------------------------------------------------------------------------
# THE A/B: flagship transformer, off vs zero1 (+ quantized allgather)
# ---------------------------------------------------------------------------

def _tiny_cfg():
    from apex_tpu.models import TransformerConfig
    return TransformerConfig(vocab_size=64, max_len=16, num_layers=1,
                             d_model=32, num_heads=2, d_ff=64,
                             dtype=jnp.float32)


def _make_batch(step):
    rng = np.random.RandomState(1000 + step)
    return jnp.asarray(rng.randint(0, 64, (N_DEV, 16)).astype("int32"))


def _transformer_fns(mesh, *, sharded, rs_scheme=None, ag_scheme=None,
                     residual=False):
    """(init_state, jitted step) for the flagship transformer under DDP
    + FusedAdam(impl='fused').  ``sharded=False`` is today's path:
    per-leaf allreduce + replicated ``step_flat`` + amp's overflow
    select.  ``sharded=True`` routes through ``ShardedUpdate``.  Params
    stay replicated; grads are taken wrt a pcast-varying copy so the
    collectives actually run (wrt replicated params the cotangent rule
    pre-sums them)."""
    from apex_tpu.models import transformer_init, transformer_loss
    cfg = _tiny_cfg()
    params0 = transformer_init(jax.random.PRNGKey(0), cfg)
    opt = FusedAdam(lr=1e-2, impl="fused")
    vma_kw = {} if has_vma() else {"check_vma": False}
    pspec = jax.tree_util.tree_map(lambda _: P(), params0)

    def grads_of(params, tokens):
        pv = jax.tree_util.tree_map(
            lambda p: _to_varying(p, ("data",)), params)
        return jax.value_and_grad(lambda p: transformer_loss(
            p, {"tokens": tokens, "targets": tokens}, cfg))(pv)

    if not sharded:
        ddp = DistributedDataParallel(axis_name="data")
        state0 = opt.init(params0)
        uspec = jax.tree_util.tree_map(lambda _: P(), state0)

        def body(params, state, tokens):
            loss, grads = grads_of(params, tokens)
            grads = ddp.allreduce_grads(grads)
            fl = opt.flattener_for(params)
            flat = fl.flatten(grads)
            ok = jnp.all(jnp.isfinite(flat)).astype(jnp.float32)
            new_state = opt.step_flat(state, flat)
            new_state = jax.tree_util.tree_map(
                lambda nw, old: jnp.where(ok > 0, nw, old),
                new_state, state)
            return (fl.unflatten(new_state.master, like=params),
                    new_state, jax.lax.pmean(loss, "data"))

        step = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(pspec, uspec, P("data")),
            out_specs=(pspec, uspec, P()), **vma_kw))
        return (params0, state0), step

    su = wu.ShardedUpdate(opt, axis_name="data",
                          collective_scheme=rs_scheme,
                          allgather_scheme=ag_scheme)
    sspec = su.state_pspecs(params0, N_DEV)
    if residual:
        @functools.partial(shard_map, mesh=mesh, in_specs=(pspec,),
                           out_specs=(sspec, P("data")))
        def init_s(p):
            return su.init(p), su.init_residual(p)[None]

        def body(params, state, res, tokens):
            loss, grads = grads_of(params, tokens)
            params, state, r2 = su.step(state, grads, params,
                                        residual=res[0])
            return params, state, r2[None], jax.lax.pmean(loss, "data")

        step = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(pspec, sspec, P("data"), P("data")),
            out_specs=(pspec, sspec, P("data"), P()), **vma_kw))
        state0, res0 = jax.jit(init_s)(params0)
        return (params0, state0, res0), step

    @functools.partial(shard_map, mesh=mesh, in_specs=(pspec,),
                       out_specs=sspec)
    def init_s(p):
        return su.init(p)

    def body(params, state, tokens):
        loss, grads = grads_of(params, tokens)
        params, state = su.step(state, grads, params)
        return params, state, jax.lax.pmean(loss, "data")

    step = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(pspec, sspec, P("data")),
        out_specs=(pspec, sspec, P()), **vma_kw))
    return (params0, jax.jit(init_s)(params0)), step


def test_ab_flagship_transformer_zero1_bitwise_and_metered(mesh):
    """ACCEPTANCE: 6-step CPU-mesh training of the flagship transformer
    with ``update_sharding="zero1"`` (fp32 allgather) is BITWISE the
    unsharded fp32 run — params and losses — while the new meters carry
    the expected bytes and the optimizer-state gauge shrinks ~1/N."""
    (pu, su_state), step_u = _transformer_fns(mesh, sharded=False)

    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)
    (ps, ss_state), step_s = _transformer_fns(mesh, sharded=True)

    losses_u, losses_s = [], []
    for i in range(6):
        pu, su_state, lu = step_u(pu, su_state, _make_batch(i))
        ps, ss_state, ls = step_s(ps, ss_state, _make_batch(i))
        losses_u.append(float(lu))
        losses_s.append(float(ls))

    # training happened, and zero1 is bitwise the unsharded run
    assert losses_u[-1] < losses_u[0]
    assert losses_s == losses_u
    for (kp_a, a), (kp_b, b) in zip(
            jax.tree_util.tree_leaves_with_path(pu),
            jax.tree_util.tree_leaves_with_path(ps)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(kp_a))

    # the meters: one traced program moved flat-total fp32 bytes through
    # the reduce-scatter and shard-sized fp32 bytes through the gather
    eng = wu.ShardedUpdate(FusedAdam(lr=1e-2, impl="fused"),
                           axis_name="data")
    from apex_tpu.models import transformer_init
    fl = eng._fl(transformer_init(jax.random.PRNGKey(0), _tiny_cfg()),
                 N_DEV)
    vals = reg.read()
    assert vals["ddp.reduce_scatter_bytes"] == fl.total * 4
    assert vals["ddp.reduce_scatter_compressed_bytes"] == fl.total * 4
    assert vals["ddp.param_allgather_bytes"] == fl.total // N_DEV * 4
    assert vals["ddp.param_allgather_compressed_bytes"] == \
        fl.total // N_DEV * 4
    # optimizer-state bytes per replica: ~1/N of the replicated layout
    assert vals["ddp.opt_state_bytes_per_replica"] == pytest.approx(
        (3 * fl.total * 4 + 4) / N_DEV, rel=0.05)
    recs = reg.flush()
    assert records_violations(recs) == []
    names = {r.get("name") for r in recs if r.get("kind") == "event"}
    assert {"ddp.reduce_scatter", "ddp.param_allgather"} <= names


def test_ab_int8_allgather_compresses_within_tolerance(mesh):
    """int8_blockscale param allgather: >=3.5x fewer wire bytes (from
    the ddp.param_allgather counters) at tolerance-level loss vs the
    fp32 sharded run."""
    # the fp32 comparator runs (and traces) BEFORE the registry is
    # installed, so the counters below carry ONLY the int8 run's meters
    (p32, s32), step32 = _transformer_fns(mesh, sharded=True)
    l32 = l8 = None
    for i in range(6):
        p32, s32, l32 = step32(p32, s32, _make_batch(i))
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)
    (p8, s8), step8 = _transformer_fns(mesh, sharded=True,
                                       ag_scheme="int8_blockscale")
    for i in range(6):
        p8, s8, l8 = step8(p8, s8, _make_batch(i))
    assert abs(float(l8) - float(l32)) < 0.05 * abs(float(l32))
    vals = reg.read()
    logical = vals["ddp.param_allgather_bytes"]
    wire = vals["ddp.param_allgather_compressed_bytes"]
    assert logical / wire >= 3.5, (logical, wire)
    assert vals["ddp.param_allgather_compression_ratio"] >= 3.5


def test_env_collectives_knob_reaches_reduce_scatter_not_allgather(mesh):
    """APEX_TPU_COLLECTIVES A/Bs the gradient reduce-scatter (it IS the
    DDP gradient wire) but never implicitly quantizes the param
    allgather — the ZeRO posture."""
    os.environ[collectives.ENV_KNOB] = "int8_blockscale:min_bytes=0"
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)
    (ps, ss), step_s = _transformer_fns(mesh, sharded=True)
    ps, ss, loss = step_s(ps, ss, _make_batch(0))
    assert np.isfinite(float(loss))
    vals = reg.read()
    assert vals["ddp.reduce_scatter_compressed_bytes"] \
        < vals["ddp.reduce_scatter_bytes"]
    assert vals["ddp.param_allgather_compressed_bytes"] \
        == vals["ddp.param_allgather_bytes"]
    recs = reg.flush()
    evs = {r["name"]: r for r in recs if r.get("kind") == "event"}
    assert evs["ddp.reduce_scatter"]["fields"]["scheme"] \
        == "int8_blockscale"
    assert evs["ddp.param_allgather"]["fields"].get("scheme") \
        != "int8_blockscale"


# ---------------------------------------------------------------------------
# resilience: guard preempt/resume with sharded state in the carry
# ---------------------------------------------------------------------------

def test_guard_preempt_resume_with_sharded_state_bitwise(mesh, tmp_path):
    """Chaos acceptance (mirror of PR 7's residual test): preempt@N +
    resume with the SHARDED optimizer state (and int8 error-feedback
    residual) in the step carry is bitwise-identical to an
    uninterrupted run — the sharded state snapshots/restores cleanly
    through TrainGuard."""
    from apex_tpu.resilience import GuardConfig, TrainGuard

    (params0, state0, res0), jstep = _transformer_fns(
        mesh, sharded=True,
        rs_scheme="int8_blockscale:min_bytes=0", residual=True)

    def step_fn(state, batch):
        params, opt_state, res = state
        params, opt_state, res, loss = jstep(params, opt_state, res,
                                             batch)
        return (params, opt_state, res), loss

    def cfg(d):
        return GuardConfig(ckpt_dir=str(d), save_every_steps=4,
                           check_every=2, backoff_seconds=0.01,
                           enabled=True)

    ref_state, rep = TrainGuard(step_fn, cfg(tmp_path / "ref")).run(
        (params0, state0, res0), _make_batch, 10)
    assert rep.status == "completed"

    plan = faults.parse("preempt@6")
    d = tmp_path / "chaos"
    _, r1 = TrainGuard(step_fn, cfg(d), plan=plan).run(
        (params0, state0, res0), _make_batch, 10)
    assert r1.status == "preempted" and r1.faults_injected == 1
    state2, r2 = TrainGuard(step_fn, cfg(d), plan=plan).run(
        (params0, state0, res0), _make_batch, 10)
    assert r2.status == "completed" and r2.resumed_from is not None

    ref_leaves = jax.tree_util.tree_leaves(ref_state)
    got_leaves = jax.tree_util.tree_leaves(state2)
    assert len(ref_leaves) == len(got_leaves)
    for a, b in zip(ref_leaves, got_leaves):
        assert np.array_equal(np.asarray(a), np.asarray(b))   # bitwise
    # the sharded optimizer state is genuinely live (steps applied)
    assert int(ref_state[1].count) == 10
    res_final = jax.tree_util.tree_leaves(ref_state[2])
    assert any(float(jnp.abs(r).max()) > 0 for r in res_final)


# ---------------------------------------------------------------------------
# bench leg + apply_perf_results audit/decide + tuning schema
# ---------------------------------------------------------------------------

def _load_tool(name, rel):
    import importlib.util
    ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, *rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_update_sharding_leg_shape():
    """The bench leg: off vs zero1 (+int8 allgather) with the ~1/N
    opt-state shrink, schema-valid embedded telemetry carrying the new
    counters and the HBM fields (what apply_perf_results'
    update_sharding audit checks)."""
    bench = _load_tool("bench", ["bench.py"])
    leg = bench.bench_update_sharding(on_tpu=False)
    assert leg["leg"] == "update_sharding"
    assert set(leg["modes"]) == {"off", "zero1", "zero1_int8ag"}
    assert leg["world"] == N_DEV
    # ~1/N optimizer-state shrink, layout-matched
    assert leg["opt_state_shrink"] == pytest.approx(N_DEV, rel=0.05)
    assert leg["modes"]["zero1_int8ag"]["ag_ratio"] >= 3.5
    assert leg["modes"]["zero1"]["ag_ratio"] == 1.0
    assert leg["modes"]["zero1"]["rs_logical_bytes"] > 0
    # HBM evidence: the CPU path carries the compiled footprint
    assert leg.get("hbm_compiled_peak_bytes") or leg.get(
        "hbm_device_process_peak_bytes")
    assert records_violations(leg["telemetry"]["records"]) == []
    names = {r.get("name") for r in leg["telemetry"]["records"]}
    assert {"ddp.reduce_scatter_bytes", "ddp.param_allgather_bytes",
            "ddp.opt_state_bytes_per_replica"} <= names

    apr = _load_tool("apply_perf_results",
                     ["tools", "apply_perf_results.py"])
    art = {"backend": "tpu", "detail": {"update_sharding": leg}}
    assert apr.update_sharding_violations(art) == []
    # exempt from the MFU/HBM audit (its own audit covers the evidence)
    assert apr.perf_field_violations(art) == []
    # drifted legs are flagged: bad shrink, bad int8 ratio, bare counters
    bad = {"backend": "tpu", "detail": {"update_sharding": {
        "leg": "update_sharding", "world": 8, "opt_state_shrink": 2.0,
        "telemetry": leg["telemetry"],
        "modes": {"zero1_int8ag": {"ag_ratio": 2.0}}}}}
    vs = apr.update_sharding_violations(bad)
    assert any("opt_state_shrink" in v for v in vs)
    assert any("ratio" in v for v in vs)
    assert any("update_sharding leg embeds no telemetry" in v
               for v in apr.update_sharding_violations(
                   {"leg": "update_sharding", "modes": {}}))


def test_decide_writes_ddp_update_sharding():
    """The decide() rule: zero1 wins when no slower than off; the
    winning int8 variant with its metered ratio pins the allgather
    scheme; both keys pass the committed tuning schema."""
    apr = _load_tool("apply_perf_results",
                     ["tools", "apply_perf_results.py"])
    from apex_tpu.utils import tuning

    def art(off_ms, z_ms, z8_ms, ratio=3.9):
        return {"backend": "tpu", "detail": {"update_sharding": {
            "leg": "update_sharding", "world": 8, "opt_state_shrink": 7.9,
            "modes": {
                "off": {"step_ms": off_ms},
                "zero1": {"step_ms": z_ms, "ag_ratio": 1.0},
                "zero1_int8ag": {"step_ms": z8_ms, "ag_ratio": ratio},
            }}}}

    prof, rows = apr.decide(art(10.0, 8.0, 7.0), None)
    assert prof["ddp_update_sharding"] == "zero1"
    assert prof["ddp_update_allgather_scheme"] == "int8_blockscale"
    assert tuning.schema_violations(prof) == []

    # zero1 slower -> off; no allgather key written
    prof, _ = apr.decide(art(5.0, 8.0, 7.0), None)
    assert prof["ddp_update_sharding"] == "off"
    assert "ddp_update_allgather_scheme" not in prof

    # int8 wins on ms but its ratio drifted -> the variant is excluded
    # from the election entirely; zero1 is still elected here because
    # the fp32 variant beats off ON ITS OWN timing
    prof, _ = apr.decide(art(10.0, 8.0, 7.0, ratio=2.0), None)
    assert prof["ddp_update_sharding"] == "zero1"
    assert "ddp_update_allgather_scheme" not in prof

    # drifted int8 is fastest but the consumable fp32 variant is slower
    # than off -> off (the drifted timing must not elect zero1 on the
    # fp32 variant's behalf)
    prof, _ = apr.decide(art(7.5, 8.0, 7.0, ratio=2.0), None)
    assert prof["ddp_update_sharding"] == "off"
    assert "ddp_update_allgather_scheme" not in prof

    # fp32 zero1 wins -> no allgather key
    prof, _ = apr.decide(art(10.0, 6.0, 7.0), None)
    assert prof["ddp_update_sharding"] == "zero1"
    assert "ddp_update_allgather_scheme" not in prof
    assert tuning.schema_violations(
        {"ddp_update_sharding": "zero1",
         "ddp_update_allgather_scheme": "int8_blockscale"}) == []
    assert tuning.schema_violations(
        {"ddp_update_sharding": "maybe"}) != []


def test_tuning_profile_drives_resolve_mode(tmp_path, monkeypatch):
    """resolve_mode consults the ddp_update_sharding tuning key — but
    only on TPU (get_on_tpu); on the CPU backend the profile must NOT
    flip the mode (measured winners apply where they were measured)."""
    import json
    from apex_tpu.utils import tuning
    prof = tmp_path / "tuned_defaults.json"
    prof.write_text(json.dumps({"ddp_update_sharding": "zero1"}))
    monkeypatch.setenv("APEX_TPU_TUNING_FILE", str(prof))
    tuning.reload()
    try:
        assert tuning.get("ddp_update_sharding") == "zero1"
        assert wu.resolve_mode() == "off"       # CPU: profile not applied
        assert wu.resolve_mode("zero1") == "zero1"
    finally:
        monkeypatch.delenv("APEX_TPU_TUNING_FILE")
        tuning.reload()


# ---------------------------------------------------------------------------
# telemetry.memory: sharded m/v slices classify as optimizer
# ---------------------------------------------------------------------------

def test_classifier_sharded_state_fields():
    from apex_tpu.telemetry import memory
    assert memory.classify_arg("state.m") == "optimizer"
    assert memory.classify_arg("state.v") == "optimizer"
    assert memory.classify_arg(r"state[\'m\']") == "optimizer"
    assert memory.classify_arg("opt_state.master") == "optimizer"
    # no false positives on batch-ish names
    assert memory.classify_arg("m_tokens") == "batch"
    assert memory.classify_arg("vectors") == "args"
    # a genuine model param field literally named 'm' stays params —
    # the explicit param-name keys outrank the bare terminal heuristic
    # (the quoted ['m'] form was already an optimizer key pre-PR8)
    assert memory.classify_arg("model_params.m") == "params"


def test_memory_model_per_replica_optimizer_bytes(mesh):
    """The keypath classifier + memory_model report per-replica
    optimizer bytes under sharding: the sharded ``m``/``v``/``master``
    slices classify as optimizer (not temps), and
    ``optimizer_bytes_per_replica`` divides by the shard world."""
    from apex_tpu.telemetry import memory
    params = _flat_params()
    su = wu.ShardedUpdate(FusedAdam(lr=1e-2, impl="fused"),
                          axis_name="data")
    _, step_s, init_s, _ = _make_steps(
        mesh, FusedAdam(lr=1e-2, impl="fused"), su, params)
    state = init_s(params)
    fl = su._fl(params, N_DEV)

    table = memory.memory_table(step_s, state, _flat_grads(0), params)
    opt_bytes = table["by_class"].get("optimizer", 0)
    # the SPMD-compiled entry is per-partition-shaped: the sharded
    # state.m / state.v / state.master slices (total/N fp32 each) must
    # ALL classify as optimizer — without the terminal .m/.v rule the
    # moments would land in "args" and the per-replica optimizer
    # attribution would be a third of reality
    assert opt_bytes == 3 * (fl.total // N_DEV) * 4
    model = memory.memory_model(table=table, register=False)
    assert model["optimizer_bytes"] == opt_bytes
    assert model["optimizer_bytes_per_replica"] == opt_bytes
    assert model["update_sharding_world"] == 1

    # planning form: a REPLICATED-layout table + update_sharding_world
    # models the zero1 shrink (what one replica would hold)
    opt_u = FusedAdam(lr=1e-2, impl="fused")
    state_u = opt_u.init(params)
    flu = opt_u.flattener
    table_u = memory.memory_table(
        lambda s, g: opt_u.step_flat(s, flu.flatten(g)),
        state_u, jax.tree_util.tree_map(lambda x: x[0], _flat_grads(0)))
    model_u = memory.memory_model(table=table_u, register=False,
                                  update_sharding_world=N_DEV)
    assert model_u["optimizer_bytes"] >= 3 * flu.total * 4
    assert model_u["optimizer_bytes_per_replica"] == \
        model_u["optimizer_bytes"] // N_DEV
    assert model_u["update_sharding_world"] == N_DEV
