"""Expert-parallel MoE tests on the 8-device CPU mesh: the sharded
all-to-all routing must match the single-device MoE exactly (oracle
pattern), forward AND backward, and tokens must actually reach the right
experts."""
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from apex_tpu.parallel.mesh import shard_map   # check_vma/check_rep compat
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel.expert import MoELayer, moe_ffn

N_DEV = 8
T, D, F, E = 64, 16, 32, 8          # tokens, d_model, d_ff, experts


def _mesh():
    return Mesh(np.array(jax.devices()[:N_DEV]), ("expert",))


def _layer(n_shards):
    return MoELayer(d_model=D, d_ff=F, num_experts=E, n_shards=n_shards,
                    capacity_factor=8.0)   # big capacity: no drops -> exact


SHARD_SPEC = {"router": P(), "w_in": P("expert"), "w_out": P("expert")}


def _oracle_per_shard(params, x):
    """Single-device MoE applied per token-shard (each device routes its
    OWN tokens with per-shard capacity — the semantics of the distributed
    run with tokens sharded over the same devices)."""
    single = _layer(1)
    outs = [single.apply(params, xs)[0]
            for xs in x.reshape(N_DEV, T // N_DEV, D)]
    return jnp.concatenate(outs, axis=0)


def test_sharded_matches_single_device():
    key = jax.random.PRNGKey(0)
    params = _layer(1).init(key)
    x = jax.random.normal(jax.random.PRNGKey(1), (T, D))

    mesh = _mesh()

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(SHARD_SPEC, P("expert")),
        out_specs=(P("expert"), P()))
    def sharded(params, x):
        out, aux = moe_ffn(x, params["router"], params["w_in"],
                           params["w_out"], axis_name="expert",
                           capacity_factor=8.0)
        return out, jax.lax.pmean(aux, "expert")

    out, aux = sharded(params, x)
    ref = _oracle_per_shard(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_sharded_gradients_match():
    params = _layer(1).init(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (T, D))
    g = jax.random.normal(jax.random.PRNGKey(4), (T, D))
    mesh = _mesh()

    @jax.jit
    def dist_grads(params, x, g):
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(SHARD_SPEC, P("expert"), P("expert")),
                           out_specs=P())
        def f(params, x, g):
            out, _ = moe_ffn(x, params["router"], params["w_in"],
                             params["w_out"], axis_name="expert",
                             capacity_factor=8.0)
            return jax.lax.psum(jnp.sum(out * g), "expert")
        return jax.grad(lambda p: f(p, x, g))(params)

    @jax.jit
    def ref_grads(params, x, g):
        return jax.grad(lambda p: jnp.sum(_oracle_per_shard(p, x) * g))(
            params)

    gd, gr = dist_grads(params, x, g), ref_grads(params, x, g)
    for k in ("router", "w_in", "w_out"):
        np.testing.assert_allclose(np.asarray(gd[k]), np.asarray(gr[k]),
                                   atol=5e-5, err_msg=k)


def test_routing_reaches_argmax_expert():
    """With an identity-ish router, each token's output must come from the
    expert its argmax selects (routing correctness, not just numerics)."""
    # expert e scales tokens by (e+1) via identity w_in/w_out
    w_in = jnp.stack([jnp.eye(D, F) for _ in range(E)])
    w_out = jnp.stack([(e + 1.0) * jnp.eye(F, D) for e in range(E)])
    # positive tokens + a strong router column send every token to expert 3
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(6), (T, D))) + 0.1
    router = jnp.zeros((D, E)).at[:, 3].set(1.0)
    out, _ = moe_ffn(x, router, w_in, w_out, axis_name=None,
                     capacity_factor=float(E))
    gate = jax.nn.softmax(x.astype(jnp.float32) @ router, -1)[:, 3]
    expect = 4.0 * x * gate[:, None]    # expert 3 scales by 4, times prob
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               atol=1e-4)


def test_capacity_overflow_drops_tokens():
    """Tokens beyond an expert's capacity pass through with ZERO expert
    output (switch semantics)."""
    w_in = jnp.stack([jnp.eye(D, F) for _ in range(E)])
    w_out = jnp.stack([jnp.eye(F, D) for _ in range(E)])
    router = jnp.zeros((D, E)).at[:, 0].set(5.0)   # everyone -> expert 0
    x = jnp.ones((T, D))
    out, _ = moe_ffn(x, router, w_in, w_out, axis_name=None,
                     capacity_factor=0.25)         # capacity = 2 tokens
    capacity = max(int(0.25 * T / E), 1)
    nonzero_rows = int((np.abs(np.asarray(out)).sum(axis=1) > 1e-6).sum())
    assert nonzero_rows == capacity


def test_layer_init_shapes_and_shard_validation():
    layer = MoELayer(d_model=D, d_ff=F, num_experts=E, n_shards=4)
    params = layer.init(jax.random.PRNGKey(7))
    assert params["w_in"].shape == (2, D, F)       # 8/4 local experts
    with pytest.raises(ValueError):
        MoELayer(d_model=D, d_ff=F, num_experts=6, n_shards=4).init(
            jax.random.PRNGKey(8))
