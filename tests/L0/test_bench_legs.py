"""Incremental bench-leg persistence (round-4 verdict item 2): a tunnel
that re-wedges mid-bench must not lose completed measurements.

Covers the three layers of the recovery pipeline:
  1. ``apex_tpu.utils.bench_legs`` — flush/read/assemble primitives;
  2. ``bench.run_bench(legs_dir=...)`` flushes the headline leg after
     EVERY sub-measurement (simulated mid-run wedge keeps earlier ones);
  3. ``assemble`` rebuilds a driver-shaped (partial) payload from
     whatever legs landed, and never reports vs_baseline off-TPU.
"""
import importlib.util
import json
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.utils.bench_legs import (assemble, flush_leg, make_flusher,
                                       read_legs)


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_flush_and_read_roundtrip(tmp_path):
    d = str(tmp_path / "legs")
    flush_leg(d, "headline", {"xla_impl_ms": 1.5}, backend="tpu")
    flush_leg(d, "rn50", {"images_per_sec": 10.0}, backend="tpu")
    # re-flush overwrites (accreting legs)
    flush_leg(d, "headline", {"xla_impl_ms": 1.5, "winner": "xla"},
              backend="tpu")
    legs = read_legs(d)
    assert set(legs) == {"headline", "rn50"}
    assert legs["headline"]["data"]["winner"] == "xla"
    assert legs["headline"]["backend"] == "tpu"
    assert legs["headline"]["ts"].endswith("Z")
    # no tmp debris from the atomic writes
    assert not [f for f in os.listdir(d) if f.startswith(".")]


def test_flush_none_dir_is_noop(tmp_path):
    flush_leg(None, "headline", {"x": 1}, backend="cpu")
    flush_leg("", "headline", {"x": 1}, backend="cpu")


def test_read_legs_skips_corrupt_file(tmp_path):
    d = str(tmp_path)
    flush_leg(d, "good", {"v": 1}, backend="tpu")
    with open(os.path.join(d, "bad.json"), "w") as f:
        f.write("{truncated")
    legs = read_legs(d)
    assert set(legs) == {"good"}


def test_assemble_bench_partial_headline_only(tmp_path):
    """A window that wedged after the xla timing still yields a usable
    payload: value from the one finished impl, partial=true, and
    vs_baseline stays null (no baseline was timed)."""
    d = str(tmp_path)
    flush_leg(d, "headline", {"n_params": 100, "complete": False,
                              "xla_impl_ms": 28.8}, backend="tpu")
    out = assemble(d, "bench")
    assert out["partial"] is True
    assert out["value"] == 28.8
    assert out["vs_baseline"] is None
    assert out["backend"] == "tpu"
    assert out["leg_timestamps"]["headline"]
    assert out["detail"]["xla_impl_ms"] == 28.8


def test_assemble_bench_full_legs(tmp_path):
    d = str(tmp_path)
    flush_leg(d, "headline", {"n_params": 100, "complete": True,
                              "xla_impl_ms": 28.8,
                              "fused_flat_impl_ms": 19.0,
                              "optax_baseline_ms": 29.4,
                              "winner": "fused_flat"}, backend="tpu")
    flush_leg(d, "rn50", {"images_per_sec": 800.0, "batch": 128},
              backend="tpu")
    flush_leg(d, "bert_e2e", {"step_ms": 900.0}, backend="tpu")
    out = assemble(d, "bench")
    assert out["value"] == 19.0
    assert out["vs_baseline"] == pytest.approx(29.4 / 19.0, abs=1e-3)
    assert out["detail"]["rn50"]["images_per_sec"] == 800.0
    assert out["detail"]["bert_e2e"]["step_ms"] == 900.0
    assert out["partial"] is True        # assembled => documents a kill


def test_assemble_bench_cpu_backend_never_reports_vs_baseline(tmp_path):
    """round-4 verdict weak #3: a CPU ratio must not surface as
    vs_baseline even through the assembler path."""
    d = str(tmp_path)
    flush_leg(d, "headline", {"xla_impl_ms": 16.7,
                              "optax_baseline_ms": 21.0}, backend="cpu")
    out = assemble(d, "bench")
    assert out["value"] == 16.7
    assert out["vs_baseline"] is None


def test_assemble_kernels_merges_sections(tmp_path):
    d = str(tmp_path)
    flush_leg(d, "attention", {"flash_attn_fwd": {"pallas_ms": 1.0,
                                                  "xla_ms": 2.0}},
              backend="tpu")
    # intra-leg flush mid-sweep, then the section flush overwrote it with
    # one more row — the assembler sees only the latest
    flush_leg(d, "attn_seq_sweep",
              {"attn_seq_sweep": {"by_seq": {"64": {"speedup": 0.9}}}},
              backend="tpu")
    flush_leg(d, "attn_seq_sweep",
              {"attn_seq_sweep": {"by_seq": {"64": {"speedup": 0.9},
                                             "128": {"speedup": 1.1}}}},
              backend="tpu")
    out = assemble(d, "kernels")
    assert out["metric"] == "pallas_kernel_microbench"
    assert out["compiled"] is True
    assert out["kernels"]["flash_attn_fwd"]["xla_ms"] == 2.0
    assert set(out["kernels"]["attn_seq_sweep"]["by_seq"]) == {"64", "128"}
    assert out["partial"] is True


def test_assemble_empty_dir(tmp_path):
    """No legs => backend 'none' (not 'mixed'): nothing was measured on
    ANY backend, and downstream tooling treats 'mixed' as partially
    TPU-backed."""
    out = assemble(str(tmp_path), "bench")
    assert out["value"] is None and out["detail"] == {}
    assert out["backend"] == "none"
    out_k = assemble(str(tmp_path / "missing"), "kernels")
    assert out_k["kernels"] == {} and out_k["backend"] == "none"


def test_assemble_cli_prints_json(tmp_path):
    import subprocess
    import sys
    d = str(tmp_path)
    flush_leg(d, "headline", {"xla_impl_ms": 3.0}, backend="tpu")
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.utils.bench_legs", d,
         "--kind", "bench"],
        capture_output=True, text=True, cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    payload = json.loads(r.stdout.strip().splitlines()[-1])
    assert payload["value"] == 3.0 and payload["partial"] is True


def test_merge_flush_keeps_prior_window_measurements(tmp_path):
    """A second recovery window that wedges EARLIER than the first must
    not destroy the first window's captured timings (code-review r5)."""
    d = str(tmp_path)
    # window 1 got as far as the fused timing
    flush_leg(d, "headline", {"xla_impl_ms": 28.8,
                              "fused_flat_impl_ms": 19.0,
                              "complete": False}, backend="tpu")
    # window 2 re-measured xla (fresher value wins) then died
    flush_leg(d, "headline", {"xla_impl_ms": 27.9, "complete": False},
              backend="tpu", merge=True)
    head = read_legs(d)["headline"]["data"]
    assert head["xla_impl_ms"] == 27.9          # fresh value wins
    assert head["fused_flat_impl_ms"] == 19.0   # old survives
    out = assemble(d, "bench")
    assert out["value"] == 19.0


def test_merge_flush_deep_merges_sweep_rows(tmp_path):
    """Kernel sweep legs: a re-run that wedged earlier keeps the rows a
    previous window captured (code-review r5, second pass)."""
    d = str(tmp_path)
    flush_leg(d, "attn_seq_sweep",
              {"attn_seq_sweep": {"by_seq": {"64": 1.0, "128": 2.0,
                                             "256": 3.0}}},
              backend="tpu")
    flush_leg(d, "attn_seq_sweep",
              {"attn_seq_sweep": {"by_seq": {"64": 0.9}}},
              backend="tpu", merge=True)
    rows = read_legs(d)["attn_seq_sweep"]["data"]["attn_seq_sweep"]["by_seq"]
    assert rows == {"64": 0.9, "128": 2.0, "256": 3.0}


def test_merge_flush_never_mixes_backends(tmp_path):
    """A CPU re-run must neither inherit NOR destroy TPU-backend legs:
    the TPU measurement is the perf story, the CPU record is noise."""
    d = str(tmp_path)
    flush_leg(d, "headline", {"xla_impl_ms": 28.8}, backend="tpu")
    flush_leg(d, "headline", {"fused_flat_impl_ms": 52.0}, backend="cpu",
              merge=True)
    head = read_legs(d)["headline"]
    assert head["backend"] == "tpu"             # tpu leg preserved
    assert head["data"] == {"xla_impl_ms": 28.8}
    # and the same protection without merge (plain overwrite attempt)
    flush_leg(d, "headline", {"fused_flat_impl_ms": 52.0}, backend="cpu")
    assert read_legs(d)["headline"]["backend"] == "tpu"
    # a TPU re-run may of course overwrite a CPU leg (upgrade)
    flush_leg(d, "rn50", {"ips": 1.0}, backend="cpu")
    flush_leg(d, "rn50", {"ips": 900.0}, backend="tpu")
    assert read_legs(d)["rn50"]["data"]["ips"] == 900.0


def test_assemble_mixed_backends_tags_every_leg(tmp_path):
    """CPU and TPU legs in one dir (half-recovered tunnel): every merged
    value must carry its backend and no headline metric may surface from
    the CPU leg."""
    d = str(tmp_path)
    flush_leg(d, "headline", {"xla_impl_ms": 16.7,
                              "optax_baseline_ms": 21.0}, backend="cpu")
    flush_leg(d, "rn50", {"images_per_sec": 800.0}, backend="tpu")
    out = assemble(d, "bench")
    assert out["backend"] == "mixed"
    assert out["value"] is None                 # cpu headline: not the metric
    assert out["vs_baseline"] is None
    assert out["detail"]["_backend"] == "cpu"   # tagged headline fields
    assert out["detail"]["rn50"]["_backend"] == "tpu"

    out_k_dir = str(tmp_path / "k")
    flush_leg(out_k_dir, "attention",
              {"flash_attn_fwd": {"pallas_ms": 1.0}}, backend="tpu")
    flush_leg(out_k_dir, "xentropy",
              {"xentropy_fwd": {"pallas_ms": 9.0}}, backend="cpu")
    out_k = assemble(out_k_dir, "kernels")
    assert out_k["backend"] == "mixed"
    assert out_k["kernels"]["flash_attn_fwd"]["_backend"] == "tpu"
    assert out_k["kernels"]["xentropy_fwd"]["_backend"] == "cpu"


def test_bench_telemetry_records_schema_checked(tmp_path):
    """bench legs that embed telemetry records (bert_e2e does, via
    bench.telemetry_summary) must carry records valid against the
    committed telemetry SCHEMA, and the block must survive the
    leg-flush/assemble recovery path intact (ISSUE 3 satellite)."""
    import pytest as _pytest
    from apex_tpu.telemetry import records_violations
    bench = _load_bench()
    tel = bench.telemetry_summary([12.5], counters={"examples": 8})
    assert records_violations(tel["records"]) == []
    assert tel["summary"]["step_time_ms"]["count"] == 1
    assert tel["summary"]["step_time_ms"]["mean"] == _pytest.approx(12.5)
    # examples / (step time): the ready-made items/sec the summary carries
    assert tel["summary"]["items_per_sec"] == _pytest.approx(640.0)

    d = str(tmp_path)
    flush_leg(d, "bert_e2e", {"step_ms": 12.5, "telemetry": tel},
              backend="tpu")
    out = assemble(d, "bench")
    embedded = out["detail"]["bert_e2e"]["telemetry"]
    assert records_violations(embedded["records"]) == []
    # and the apply_perf_results auditor sees a clean artifact
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "apply_perf_results", os.path.join(ROOT, "tools",
                                           "apply_perf_results.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.telemetry_violations(out) == []


def test_leg_telemetry_lifts_mfu_and_hbm_into_gauges(tmp_path):
    """ISSUE 6 satellite: every leg embeds MFU + peak-HBM evidence as
    schema-valid gauges (bench.leg_telemetry), and the
    apply_perf_results perf-field audit accepts a leg that carries them
    and flags one that doesn't."""
    from apex_tpu.telemetry import records_violations
    bench = _load_bench()
    fields = {"mfu_pct": 41.2, "hbm_compiled_peak_bytes": 123456,
              "hbm_temp_bytes": 456}
    tel = bench.leg_telemetry([10.0], fields, counters={"examples": 4})
    assert records_violations(tel["records"]) == []
    gauges = {r["name"]: r["value"] for r in tel["records"]
              if r.get("type") == "gauge"}
    assert gauges["mfu_pct"] == 41.2
    assert gauges["mem.compiled_peak_bytes"] == 123456
    # the summary's memory line rides the same gauges
    assert tel["summary"]["mem_peak_bytes"] == 123456

    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "apply_perf_results", os.path.join(ROOT, "tools",
                                           "apply_perf_results.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    good = {"backend": "tpu",
            "detail": {"bert_e2e": {"step_ms": 10.0, "mfu_pct": 41.2,
                                    "hbm_compiled_peak_bytes": 123456,
                                    "telemetry": tel}}}
    assert mod.perf_field_violations(good) == []
    # gauges alone (no leg-dict fields) also satisfy the audit
    gauges_only = {"backend": "tpu",
                   "detail": {"bert_e2e": {"step_ms": 10.0,
                                           "telemetry": tel}}}
    assert mod.perf_field_violations(gauges_only) == []
    bare = {"backend": "tpu",
            "detail": {"bert_e2e": {
                "step_ms": 10.0,
                "telemetry": bench.telemetry_summary([10.0])}}}
    bad = mod.perf_field_violations(bare)
    assert any("peak-HBM" in v for v in bad)
    assert any("MFU" in v for v in bad)
    # hbm_util_pct is a RATIO, not the footprint — it must not satisfy
    # the byte-evidence requirement (the round-5 regression the audit
    # exists to catch)
    ratio_only = {"backend": "tpu",
                  "detail": {"bert_e2e": {
                      "step_ms": 10.0, "mfu_pct": 41.2,
                      "hbm_util_pct": 55.0,
                      "telemetry": bench.telemetry_summary([10.0])}}}
    assert any("peak-HBM" in v
               for v in mod.perf_field_violations(ratio_only))
    # CPU stand-in legs inside a mixed artifact are tagged _backend and
    # skipped — they honestly carry no MFU
    mixed = {"backend": "mixed",
             "detail": {"rn50": {
                 "step_ms": 10.0, "_backend": "cpu",
                 "telemetry": bench.telemetry_summary([10.0])}}}
    assert mod.perf_field_violations(mixed) == []


def test_mem_fields_compiled_footprint_on_cpu():
    """bench._mem_fields embeds the compiled memory_analysis footprint
    even on CPU (the allocator counters are TPU-only), so CPU runs and
    tier-1 exercise the exact field path the TPU legs emit."""
    import jax
    import jax.numpy as jnp
    bench = _load_bench()
    jitted = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((64, 64))
    jitted(x)
    out = bench._mem_fields(jitted, (x,))
    assert "mem_error" not in out, out
    assert out["hbm_compiled_peak_bytes"] > 0
    assert out["hbm_args_bytes"] == 64 * 64 * 4
    # CPU allocator reports nothing -> no device fields, no error
    assert "hbm_device_process_peak_bytes" not in out


# ---------------------------------------------------------------------------
# run_bench integration: the flush sequence under a simulated mid-run wedge
# ---------------------------------------------------------------------------

class _Wedge(Exception):
    """Stands in for the tunnel dying mid-bench (in reality: SIGKILL)."""


def _stub_timings(bench, monkeypatch, wedge_at=None):
    """Replace the slow timing fns with constants; ``wedge_at`` names the
    one that simulates the tunnel dying mid-measurement."""
    vals = {"time_apex_xla": 28.8, "time_apex_fused_flat": 19.0,
            "time_optax": 29.4}

    def mk(name, v):
        def f(*a, **k):
            if name == wedge_at:
                raise _Wedge(name)
            return v
        return f

    for name, v in vals.items():
        monkeypatch.setattr(bench, name, mk(name, v))
    monkeypatch.setattr(bench, "bench_rn50",
                        mk("bench_rn50",
                           {"images_per_sec": 1.0, "batch": 4}))
    monkeypatch.setattr(bench, "bench_rn50_native_baseline",
                        mk("bench_rn50_native_baseline",
                           {"images_per_sec": 0.8, "batch": 4}))
    monkeypatch.setattr(bench, "bench_bert_e2e",
                        mk("bench_bert_e2e", {"step_ms": 2.0}))
    monkeypatch.setattr(bench, "bench_collectives",
                        mk("bench_collectives",
                           {"leg": "collectives",
                            "schemes": {"int8_blockscale":
                                        {"host_ms": 1.0, "ratio": 3.88}}}))
    monkeypatch.setattr(bench, "bench_update_sharding",
                        mk("bench_update_sharding",
                           {"leg": "update_sharding", "world": 8,
                            "opt_state_shrink": 7.9,
                            "modes": {"off": {"step_ms": 2.0},
                                      "zero1": {"step_ms": 1.5}}}))
    monkeypatch.setattr(bench, "bench_spmd",
                        mk("bench_spmd",
                           {"leg": "spmd", "chips": 8,
                            "families": {"dp_tp": {"step_ms": 2.0}}}))
    monkeypatch.setattr(bench, "bench_goodput",
                        mk("bench_goodput",
                           {"leg": "goodput", "steps": 10,
                            "goodput_fraction": 0.9}))
    monkeypatch.setattr(bench, "bench_overlap",
                        mk("bench_overlap",
                           {"leg": "overlap", "scheme": "fp32",
                            "parity_ok": True,
                            "logical_bytes_equal": True,
                            "modes": {"off": {"step_ms": 2.0},
                                      "bucketed": {"step_ms": 1.8}}}))
    monkeypatch.setattr(bench, "bench_ppep",
                        mk("bench_ppep",
                           {"leg": "ppep", "parity_ok": True,
                            "families": {"pp": {"parity_ok": True},
                                         "ep": {"parity_ok": True}}}))
    monkeypatch.setattr(bench, "bench_serve",
                        mk("bench_serve",
                           {"leg": "serve", "requests": 16,
                            "variants": [{"olevel": "bf16",
                                          "decode_width": 8,
                                          "tokens_per_sec": 1500.0}],
                            "winner": {"olevel": "bf16",
                                       "decode_width": 8,
                                       "tokens_per_sec": 1500.0}}))
    monkeypatch.setattr(bench, "bench_plan",
                        mk("bench_plan",
                           {"leg": "plan", "chips": 8,
                            "candidates_enumerated": 27,
                            "calibration_error_pct": 3.0,
                            "plans": [{"knobs": {"dp": 8},
                                       "predicted_ms": 1.9,
                                       "measured_ms": 2.0},
                                      {"knobs": {"dp": 8,
                                                 "update_sharding":
                                                 "zero1"},
                                       "predicted_ms": 1.6,
                                       "measured_ms": 1.5}]}))


def test_run_bench_flushes_headline_incrementally(tmp_path, monkeypatch):
    """Wedge during the fused timing: the already-measured xla number is
    on disk, complete=false, and no later leg files exist."""
    bench = _load_bench()
    _stub_timings(bench, monkeypatch, wedge_at="time_apex_fused_flat")
    d = str(tmp_path / "legs")
    with pytest.raises(_Wedge):
        bench.run_bench(legs_dir=d)
    legs = read_legs(d)
    assert set(legs) == {"headline"}
    head = legs["headline"]["data"]
    assert head["xla_impl_ms"] == 28.8
    assert head["complete"] is False
    assert "fused_flat_impl_ms" not in head
    # and the assembler turns the wreckage into a driver-shaped payload
    out = assemble(d, "bench")
    assert out["value"] == 28.8 and out["partial"] is True


def test_run_bench_full_flush_sequence(tmp_path, monkeypatch):
    """No wedge: headline (complete=true) + rn50 + bert legs all land,
    and the returned payload matches the legs.  Off-TPU, vs_baseline is
    null at top level with the ratio kept as an explicit cpu proxy."""
    import jax
    bench = _load_bench()
    _stub_timings(bench, monkeypatch)
    d = str(tmp_path / "legs")
    payload = bench.run_bench(legs_dir=d)
    legs = read_legs(d)
    rn50_key = ("rn50" if jax.default_backend() == "tpu"
                else "rn50_cpu_standin_resnet18")
    assert set(legs) == {"headline", rn50_key, "bert_e2e", "collectives",
                         "update_sharding", "plan", "spmd", "overlap",
                         "ppep", "goodput", "serve"}
    assert legs["ppep"]["data"]["leg"] == "ppep"
    assert legs["serve"]["data"]["leg"] == "serve"
    assert legs["collectives"]["data"]["leg"] == "collectives"
    assert legs["goodput"]["data"]["leg"] == "goodput"
    assert legs["overlap"]["data"]["leg"] == "overlap"
    assert legs["update_sharding"]["data"]["leg"] == "update_sharding"
    assert legs["plan"]["data"]["leg"] == "plan"
    assert legs["spmd"]["data"]["leg"] == "spmd"
    assert legs["headline"]["data"]["complete"] is True
    assert legs["headline"]["data"]["winner"] == "fused_flat"
    assert payload["value"] == 19.0
    assert payload["vs_baseline"] is None          # CPU in tests
    assert payload["detail"]["vs_baseline_cpu_proxy"] == pytest.approx(
        29.4 / 19.0, abs=1e-3)
    rn50 = payload["detail"][rn50_key]
    assert rn50["images_per_sec"] == 1.0
    # the same-batch native-optax baseline rides inside the rn50 leg with
    # the ready-made ratio (BASELINE's ">=90% of native" check)
    assert rn50["native_optax_baseline"]["images_per_sec"] == 0.8
    assert rn50["vs_native_baseline"] == pytest.approx(1.25, abs=1e-3)


def test_run_bench_without_legs_dir_still_returns_payload(monkeypatch):
    bench = _load_bench()
    _stub_timings(bench, monkeypatch)
    payload = bench.run_bench()     # legs_dir=None: flushing is a no-op
    assert payload["metric"] == "fused_lamb_step_ms_bert_large"
    assert payload["value"] == 19.0


# ---------------------------------------------------------------------------
# bench_kernels section-level resume (r5: the tunnel flaps on minute-scale
# windows — a fresh window must skip already-captured sections instead of
# restarting at bench_attention and never reaching the deeper ones)
# ---------------------------------------------------------------------------

def _load_kernels():
    spec = importlib.util.spec_from_file_location(
        "bench_kernels", os.path.join(ROOT, "bench_kernels.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ab_rec(p, x):
    return {"pallas_ms": p, "xla_ms": x}


_SEQ_LABEL = "B8 H16 D64 fwd+bwd grads(q,k,v)"   # bench_kernels.ATTN_SWEEP_LABEL

_COMPLETE_LEGS = {
    "attention": {"flash_attn_fwd": _ab_rec(1.0, 1.5),
                  "flash_attn_fwdbwd": _ab_rec(2.0, 2.5),
                  "flash_attn_fwdbwd_qkv": _ab_rec(3.0, 3.5)},
    "xentropy": {"xentropy_fwd": _ab_rec(1.4, 2.7),
                 "xentropy_fwdbwd": _ab_rec(2.8, 5.4)},
    "layer_norm": {"layer_norm_fwd": _ab_rec(1.0, 1.0),
                   "layer_norm_fwdbwd": _ab_rec(1.0, 1.0)},
    "mlp": {"mlp_fwd": _ab_rec(1.0, 1.0), "mlp_fwdbwd": _ab_rec(1.0, 1.0)},
    "multi_tensor": {"l2norm": _ab_rec(1.0, 1.0),
                     "scale_flagged": _ab_rec(1.0, 1.0),
                     "axpby_flagged": _ab_rec(1.0, 1.0),
                     "adam_update": _ab_rec(1.0, 1.0),
                     "lamb_stage1": _ab_rec(1.0, 1.0)},
    # the sweep sections (flash_autotune, flash_bwd_autotune,
    # attn_seq_sweep) are injected per-test from the loaded module's own
    # ladder constants (drift guard: the bench loop, the completeness
    # row names, and this fixture share one constant — ADVICE r5 #2)
    "flash_vmem_probe": {"flash_vmem_probe": {"rows": []}},
}

_SECTION_FNS = ("bench_attention", "bench_xentropy",
                "bench_flash_bwd_autotune", "bench_layer_norm", "bench_mlp",
                "bench_multi_tensor", "bench_flash_autotune",
                "bench_attn_seq_sweep", "bench_flash_vmem_probe")


def _bwd_autotune_rec(bk, sweep):
    return {"shape": bk.FLASH_BWD_LABEL, "sweep_ms": sweep,
            "best": "128x128", "best_dq": "128x128",
            "best_dkv": "128x128", "best_fused": "128x128"}


def _complete_legs(bk):
    legs = dict(_COMPLETE_LEGS)
    assert bk.ATTN_SWEEP_LABEL == _SEQ_LABEL
    legs["attn_seq_sweep"] = {"attn_seq_sweep": {
        "shape": bk.ATTN_SWEEP_LABEL,
        "by_seq": {str(s): _ab_rec(1.0, 1.0)
                   for s in bk.ATTN_SWEEP_SEQS}}}
    legs["flash_autotune"] = {"flash_autotune": {
        "sweep_ms": {c: 1.0 for c in bk.FLASH_AUTOTUNE_LADDER},
        "best": "128x512"}}
    legs["flash_bwd_autotune"] = {"flash_bwd_autotune": _bwd_autotune_rec(
        bk, {r: 1.0 for r in bk.FLASH_BWD_ROWS})}
    return legs


def _patch_sections(bk, monkeypatch, calls):
    for name in _SECTION_FNS:
        def rec(results, on_tpu, flush=None, _n=name):
            calls.append(_n)
        rec.__name__ = name   # run() derives the leg name from fn.__name__
        monkeypatch.setattr(bk, name, rec)


def test_kernel_bench_resume_skips_complete_sections(tmp_path, monkeypatch):
    bk = _load_kernels()
    monkeypatch.setattr(bk.jax, "default_backend", lambda: "tpu")
    d = str(tmp_path / "legs")
    for leg, data in _complete_legs(bk).items():
        flush_leg(d, leg, data, backend="tpu")
    calls = []
    _patch_sections(bk, monkeypatch, calls)
    out = bk.run(legs_dir=d)
    assert calls == []                       # every section skipped
    assert out["kernels"]["xentropy_fwd"] == _ab_rec(1.4, 2.7)
    assert out["backend"] == "tpu"


def test_kernel_bench_resume_reruns_incomplete_sweep(tmp_path, monkeypatch):
    bk = _load_kernels()
    monkeypatch.setattr(bk.jax, "default_backend", lambda: "tpu")
    d = str(tmp_path / "legs")
    legs = _complete_legs(bk)
    # seq sweep captured only 3 of 6 rows; attention leg predates the
    # fwdbwd_qkv key (the r5 first capture's exact shape)
    legs["attn_seq_sweep"] = {"attn_seq_sweep": {
        "shape": _SEQ_LABEL,
        "by_seq": {"64": _ab_rec(1.0, 1.0), "128": _ab_rec(1.0, 1.0),
                   "256": _ab_rec(1.0, 1.0)}}}
    legs["attention"] = {"flash_attn_fwd": {"pallas_ms": 0.0},
                         "flash_attn_fwdbwd": {"pallas_ms": 192.9}}
    for leg, data in legs.items():
        flush_leg(d, leg, data, backend="tpu")
    calls = []
    _patch_sections(bk, monkeypatch, calls)

    def remeasuring_attention(results, on_tpu, flush=None):
        calls.append("bench_attention")
        results["flash_attn_fwd"] = {"pallas_ms": 5.5}   # repaired reading
    remeasuring_attention.__name__ = "bench_attention"
    monkeypatch.setattr(bk, "bench_attention", remeasuring_attention)
    bk.run(legs_dir=d)
    assert calls == ["bench_attention", "bench_attn_seq_sweep"]
    # a re-run section re-flushes its declared keys: the stale 0.0 ms
    # reading in the leg file must be repaired, not frozen forever by
    # the resume seeding (code-review r5)
    att = read_legs(d)["attention"]["data"]
    assert att["flash_attn_fwd"] == {"pallas_ms": 5.5}


def test_kernel_bench_cpu_run_ignores_tpu_legs(tmp_path, monkeypatch):
    """A CPU fallback must not seed TPU numbers into its own payload."""
    bk = _load_kernels()
    d = str(tmp_path / "legs")
    for leg, data in _complete_legs(bk).items():
        flush_leg(d, leg, data, backend="tpu")
    calls = []
    _patch_sections(bk, monkeypatch, calls)
    out = bk.run(legs_dir=d)                 # ambient backend = cpu
    assert len(calls) == len(_SECTION_FNS)   # nothing skipped
    assert "xentropy_fwd" not in out["kernels"]


def test_kernel_bench_transient_failure_rows_do_not_settle(tmp_path,
                                                           monkeypatch):
    """A mid-sweep tunnel collapse recorded as an error row must re-run on
    the next window; a permanent (Mosaic/compile) failure must not."""
    bk = _load_kernels()
    monkeypatch.setattr(bk.jax, "default_backend", lambda: "tpu")
    d = str(tmp_path / "legs")
    legs = _complete_legs(bk)
    sweep = {r: 1.0 for r in bk.FLASH_BWD_ROWS}
    flaky_row = bk.FLASH_BWD_ROWS[0]
    sweep[flaky_row] = "failed: XlaRuntimeError('INTERNAL: stream closed')"
    legs["flash_bwd_autotune"] = {
        "flash_bwd_autotune": _bwd_autotune_rec(bk, sweep)}
    for leg, data in legs.items():
        flush_leg(d, leg, data, backend="tpu")
    calls = []
    _patch_sections(bk, monkeypatch, calls)
    bk.run(legs_dir=d)
    assert calls == ["bench_flash_bwd_autotune"]    # transient -> retry

    # flip the row to a permanent Mosaic failure: now settled, no re-run
    sweep[flaky_row] = "failed: Mosaic lowering: RESOURCE_EXHAUSTED vmem"
    flush_leg(d, "flash_bwd_autotune", {
        "flash_bwd_autotune": _bwd_autotune_rec(bk, sweep)}, backend="tpu")
    calls.clear()
    bk.run(legs_dir=d)
    assert calls == []


def test_kernel_bench_ladder_revision_reopens_sweep(tmp_path, monkeypatch):
    """A leg captured by an OLDER ladder (enough settled rows to fool a
    count, but different row names/label) must not freeze the section
    "complete" — completeness keys on the current ladder's row NAMES
    (ADVICE r5 #2: the r5 gate counted 8 settled rows, so the r5-shaped
    record below would have skipped the rebuilt per-kernel sweep forever)."""
    bk = _load_kernels()
    monkeypatch.setattr(bk.jax, "default_backend", lambda: "tpu")
    d = str(tmp_path / "legs")
    legs = _complete_legs(bk)
    legs["flash_bwd_autotune"] = {"flash_bwd_autotune": {
        "shape": "B8 H16 S1024 D64 causal bwd-only(dq,dk,dv)",
        "sweep_ms": {c: 1.0 for c in ("128x128", "128x256", "256x256",
                                      "256x512", "512x512", "512x1024",
                                      "1024x1024", "jax_ref_fwdbwd")},
        "best": "128x128"}}
    for leg, data in legs.items():
        flush_leg(d, leg, data, backend="tpu")
    calls = []
    _patch_sections(bk, monkeypatch, calls)
    bk.run(legs_dir=d)
    assert calls == ["bench_flash_bwd_autotune"]


def test_kernel_bench_seq_sweep_stale_semantics_reset(tmp_path, monkeypatch):
    """by_seq rows measured by an older revision (different shape label)
    must not satisfy completeness nor leak into the new sweep."""
    bk = _load_kernels()
    monkeypatch.setattr(bk.jax, "default_backend", lambda: "tpu")
    d = str(tmp_path / "legs")
    legs = _complete_legs(bk)
    legs["attn_seq_sweep"] = {"attn_seq_sweep": {
        "shape": "B8 H16 D64 fwd+bwd(dq)",          # the r4 measurement
        "by_seq": {str(s): _ab_rec(1.0, 1.0)
                   for s in (64, 128, 256, 512, 1024, 2048)}}}
    for leg, data in legs.items():
        flush_leg(d, leg, data, backend="tpu")
    calls = []
    _patch_sections(bk, monkeypatch, calls)
    bk.run(legs_dir=d)
    assert calls == ["bench_attn_seq_sweep"]
