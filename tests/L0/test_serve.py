"""apex_tpu.serve (ISSUE 18): continuous-batching inference engine with
a paged KV cache, inference O-levels, and a per-request latency ledger.

The load-bearing contracts, in test order:

  1. Paged KV cache: fixed-size pages from a preallocated pool,
     all-or-nothing allocation, typed ``KVCacheExhaustedError`` — pool
     pressure degrades to shedding, never to OOM or a silent drop.
  2. THE bitwise contract: decoding token-by-token over the paged
     cache is BITWISE identical to the engine's own one-shot forward
     over the final sequence — paging, page-table gather, scatter and
     masking introduce ZERO numerical difference.  The oracle is the
     engine's own prefill on the full sequence (same compiled program,
     operand-parameterized row), NOT ``transformer_apply``: two
     separately compiled XLA programs differ by ~1 ulp on sporadic
     rows (value-dependent fusion rounding, measured on CPU), so the
     trainer forward anchors via allclose while the serving invariant
     is asserted exactly.
  3. Continuous batching is invisible: a request decoded alongside
     other requests — admissions, evictions, page recycling mid-run —
     produces the same tokens as the same request served alone.
  4. Per-request sampling PRNG keyed by (seed, position): sampled
     decodes replay deterministically, independent of slot placement.
  5. The serve ledger partitions every request's wall time EXACTLY
     (integer microseconds, tolerance zero) across the five classes.
  6. ``request_flood`` chaos: a synthetic admission burst exhausts the
     pool into typed, metered shedding.
  7. The perf loop closes: bench-serve-leg-shaped artifact ->
     ``serve_violations`` clean -> ``decide()`` persists
     ``serve_decode_batch`` / ``serve_olevel`` -> tuning schema valid.
"""
import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.models import TransformerConfig, transformer_apply, \
    transformer_init
from apex_tpu.resilience import faults
from apex_tpu.serve import (CacheConfig, ContinuousBatcher,
                            InferenceEngine, KVCacheExhaustedError, OLEVELS,
                            PagePool, Request, prepare_olevel, request_key,
                            sample_token)
from apex_tpu.serve.cache import SCRATCH_PAGE
from apex_tpu.telemetry import serve_ledger as sl
from apex_tpu.telemetry.serve_ledger import ServeLedger, serve_violations

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# shared tiny model (compiles are the cost on CPU: share engines)
# ---------------------------------------------------------------------------

CFG = TransformerConfig(vocab_size=64, max_len=32, num_layers=2,
                        d_model=32, num_heads=2, d_ff=64,
                        causal=True, xent_impl="xla")
CACHE = CacheConfig(page_size=8, num_pages=16, max_ctx=32)


@pytest.fixture(scope="module")
def params():
    return transformer_init(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def eng_fp32(params):
    return InferenceEngine(params, CFG, cache=CACHE, olevel="fp32",
                           decode_width=2)


@pytest.fixture(scope="module")
def eng_bf16(params):
    return InferenceEngine(params, CFG, cache=CACHE, olevel="bf16",
                           decode_width=4)


def _serve_one(engine, req):
    """Reference: the request served ALONE on a fresh batcher (same
    engine: the pool is shared but page-table gathers mask its
    content, so stale pages are invisible by construction)."""
    bat = ContinuousBatcher(engine)
    bat.submit(req)
    return bat.run()[req.rid]


# ---------------------------------------------------------------------------
# 1. paged KV cache: pool discipline + typed exhaustion
# ---------------------------------------------------------------------------

def test_cache_config_validation():
    with pytest.raises(ValueError):
        CacheConfig(page_size=8, num_pages=1, max_ctx=8)   # scratch only
    with pytest.raises(ValueError):
        CacheConfig(page_size=8, num_pages=4, max_ctx=12)  # not page-mult
    c = CacheConfig(page_size=8, num_pages=4, max_ctx=16)
    assert c.pages_per_request == 2
    assert [c.pages_for(n) for n in (1, 8, 9, 16)] == [1, 1, 2, 2]


def test_pool_alloc_all_or_nothing_typed():
    pool = PagePool(CacheConfig(page_size=8, num_pages=4, max_ctx=16))
    assert pool.free_pages == 3            # page 0 is the scratch page
    got = pool.alloc(2)
    assert len(got) == 2 and SCRATCH_PAGE not in got
    with pytest.raises(KVCacheExhaustedError) as ei:
        pool.alloc(2)                       # only 1 free: all-or-nothing
    assert ei.value.requested == 2 and ei.value.free == 1
    assert pool.free_pages == 1             # failed alloc took nothing
    pool.free(got)
    assert pool.free_pages == 3


def test_pool_free_is_checked():
    pool = PagePool(CacheConfig(page_size=8, num_pages=4, max_ctx=16))
    got = pool.alloc(1)
    pool.free(got)
    with pytest.raises(ValueError):
        pool.free(got)                      # double free
    with pytest.raises(ValueError):
        pool.free([SCRATCH_PAGE])           # never allocatable
    with pytest.raises(ValueError):
        pool.free([99])                     # out of range


# ---------------------------------------------------------------------------
# O-levels
# ---------------------------------------------------------------------------

def test_prepare_olevel_table(params):
    assert set(OLEVELS) == {"fp32", "bf16", "int8"}
    with pytest.raises(ValueError):
        prepare_olevel(params, "fp8")
    _, _, dt32, cr32 = prepare_olevel(params, "fp32")
    _, _, dt16, _cr16 = prepare_olevel(params, "bf16")
    _, _, _dt8, cr8 = prepare_olevel(params, "int8")
    assert dt32 == jnp.float32 and dt16 == jnp.bfloat16
    assert cr32 is None              # a ratio is only metered below int8
    # int8 block-scaled weights: the metered ratio the ledger reports
    assert cr8 > 1.0


def test_int8_dequant_close_to_fp32(params, eng_fp32):
    eng8 = InferenceEngine(params, CFG, cache=CACHE, olevel="int8",
                           decode_width=2)
    prompt = [3, 9, 4, 2, 7]
    r32 = _serve_one(eng_fp32, Request(rid="a", prompt=prompt,
                                       max_new_tokens=4))
    r8 = _serve_one(eng8, Request(rid="a", prompt=prompt,
                                  max_new_tokens=4))
    # int8 weights are lossy: decode COMPLETES with valid tokens; no
    # numeric claim beyond range (greedy argmax may legitimately flip)
    assert r8.status == r32.status == "done"
    assert all(0 <= t < CFG.vocab_size for t in r8.tokens)


def test_decode_width_floor():
    with pytest.raises(ValueError):
        InferenceEngine({"x": jnp.zeros(())}, CFG, cache=CACHE,
                        decode_width=1)


# ---------------------------------------------------------------------------
# 2. THE bitwise contract (tentpole)
# ---------------------------------------------------------------------------

def _oracle_row(eng, full_seq, t):
    """Row ``t`` of the engine's one-shot forward over ``full_seq``:
    prefill the full sequence with ``prompt_len = t + 1`` on a FRESH
    page table — the same compiled program extracts the row as an
    operand-parameterized slice, and the scratch table keeps the
    oracle's KV writes off the request's pages."""
    toks = np.zeros(CACHE.max_ctx, np.int32)
    toks[:len(full_seq)] = full_seq
    table = np.arange(12, 12 + CACHE.pages_per_request, dtype=np.int32)
    _, logits = eng.prefill(toks, t + 1, table, 0)
    return logits


def test_paged_decode_bitwise_matches_one_shot(eng_fp32):
    """Greedy decode over the paged cache, one token at a time, against
    the engine's own one-shot forward on the final sequence: every
    step's logits row must match BITWISE.  This is the invariant that
    makes paged serving trustworthy — the cache layout is invisible."""
    eng = eng_fp32
    prompt = [5, 11, 3, 8, 2]
    n_new = 6
    pool = PagePool(CACHE)
    pages = pool.alloc(CACHE.pages_for(len(prompt)))
    table = np.zeros(CACHE.pages_per_request, np.int32)
    table[:len(pages)] = pages

    toks = np.zeros(CACHE.max_ctx, np.int32)
    toks[:len(prompt)] = prompt
    first, prefill_logits = eng.prefill(toks, len(prompt), table, 0)
    seq = list(prompt) + [int(first)]

    # the prefill row itself must equal the oracle at t = plen - 1
    ref = _oracle_row(eng, prompt, len(prompt) - 1)
    np.testing.assert_array_equal(np.asarray(prefill_logits),
                                  np.asarray(ref))

    W, PPR = eng.decode_width, CACHE.pages_per_request
    for _ in range(n_new):
        pos = len(seq) - 1
        need = CACHE.pages_for(pos + 1)
        if need > len(pages):
            pages += pool.alloc(need - len(pages))
            table[:len(pages)] = pages
        toks_w = np.zeros(W, np.int32)
        toks_w[0] = seq[-1]
        positions = np.zeros(W, np.int32)
        positions[0] = pos
        tables = np.zeros((W, PPR), np.int32)
        tables[0] = table
        z = np.zeros(W, np.int32)
        nxt, dec_logits = eng.decode_step(toks_w, positions, tables, z,
                                          np.zeros(W, np.float32), z)
        ref = _oracle_row(eng, seq, pos)
        np.testing.assert_array_equal(np.asarray(dec_logits)[0],
                                      np.asarray(ref))
        seq.append(int(np.asarray(nxt)[0]))
    pool.free(pages)


def test_engine_allclose_vs_trainer_forward(params, eng_fp32):
    """The trainer forward (``transformer_apply``) anchors the engine
    numerically — allclose, NOT bitwise: two separately compiled XLA
    programs differ by ~1 ulp on sporadic logit rows (value-dependent
    fusion rounding; measured, not controllable via barriers on CPU).
    The exact contract lives in the one-shot-oracle test above."""
    prompt = [5, 11, 3, 8, 2]
    res = _serve_one(eng_fp32, Request(rid="q", prompt=prompt,
                                       max_new_tokens=5))
    seq = prompt + res.tokens
    ref_logits = transformer_apply(params, jnp.asarray([seq]), CFG)[0]
    # greedy-decode the reference forward over the same positions
    for i in range(len(prompt) - 1, len(seq) - 1):
        assert int(jnp.argmax(ref_logits[i])) == seq[i + 1]
    # and the logits agree to float32 tolerance at the prefill row
    toks = np.zeros(CACHE.max_ctx, np.int32)
    toks[:len(seq)] = seq
    table = np.arange(12, 12 + CACHE.pages_per_request, dtype=np.int32)
    _, eng_row = eng_fp32.prefill(toks, len(prompt), table, 0)
    np.testing.assert_allclose(np.asarray(eng_row),
                               np.asarray(ref_logits[len(prompt) - 1]),
                               rtol=2e-6, atol=2e-6)


# ---------------------------------------------------------------------------
# 3/4. continuous batching: invisible batching, deterministic replay
# ---------------------------------------------------------------------------

def test_batched_serving_matches_solo_reference(eng_fp32):
    """Six requests through two slots: admissions, finishes and page
    recycling mid-run — every request's tokens equal its solo-served
    reference, i.e. batching and eviction are bitwise-invisible."""
    reqs = [Request(rid=f"q{i}", prompt=[2 + i, 7, 3 + 2 * i, 5],
                    max_new_tokens=3 + (i % 3),
                    temperature=0.8 if i % 2 else 0.0,
                    top_k=8 if i % 2 else 0, seed=41 + i)
            for i in range(6)]
    bat = ContinuousBatcher(eng_fp32)
    for r in reqs:
        bat.submit(r)
    results = bat.run()
    assert all(results[r.rid].status == "done" for r in reqs)
    # the batcher drained: every page back in the pool
    assert bat.pool.free_pages == CACHE.num_pages - 1
    for r in reqs:
        solo = _serve_one(eng_fp32, r)
        assert results[r.rid].tokens == solo.tokens, r.rid


def test_sampled_replay_is_deterministic(eng_fp32):
    req = Request(rid="s", prompt=[9, 1, 4], max_new_tokens=6,
                  temperature=1.1, top_k=12, seed=123)
    a = _serve_one(eng_fp32, req)
    b = _serve_one(eng_fp32, req)
    assert a.tokens == b.tokens and len(a.tokens) == 6
    # a different seed must (for this many draws) diverge
    c = _serve_one(eng_fp32, dataclasses_replace(req, seed=124))
    assert c.tokens != a.tokens


def dataclasses_replace(req, **kw):
    import dataclasses
    return dataclasses.replace(req, **kw)


def test_sampling_key_is_positional():
    k1 = request_key(7, 3)
    k2 = request_key(7, 3)
    k3 = request_key(7, 4)
    assert jnp.array_equal(k1, k2) and not jnp.array_equal(k1, k3)
    logits = jnp.asarray([0.1, 5.0, 0.2, 4.9])
    # greedy ignores the key entirely
    t = sample_token(logits, k1, 0.0, 0)
    assert int(t) == 1
    # top-2 sampling can only land on the top-2 set
    for pos in range(8):
        t = sample_token(logits, request_key(0, pos), 1.5, 2)
        assert int(t) in (1, 3)


def test_eos_stops_early(eng_fp32):
    base = Request(rid="e0", prompt=[5, 11, 3, 8, 2], max_new_tokens=8)
    ref = _serve_one(eng_fp32, base)
    eos = ref.tokens[2]
    res = _serve_one(eng_fp32, dataclasses_replace(base, rid="e1",
                                                   eos_id=eos))
    # stops AT the first occurrence of the eos token (greedy decode can
    # repeat, so index the reference rather than assume position 2)
    cut = ref.tokens.index(eos) + 1
    assert res.tokens == ref.tokens[:cut]
    assert len(res.tokens) < len(ref.tokens)


def test_prompt_too_long_is_typed_shed(eng_fp32):
    bat = ContinuousBatcher(eng_fp32)
    bat.submit(Request(rid="big", prompt=[1] * CACHE.max_ctx,
                       max_new_tokens=2))
    res = bat.run()["big"]
    assert res.status == "shed" and res.reason == "prompt_too_long"


def test_pool_exhaustion_degrades_to_typed_shedding(params):
    """Concurrent demand above the pool: admission shedding is TYPED
    (``kv_cache_exhausted``), pages recycle, the engine never raises
    out of ``run`` and never silently drops a request."""
    small = CacheConfig(page_size=8, num_pages=8, max_ctx=32)
    eng = InferenceEngine(params, CFG, cache=small, olevel="bf16",
                          decode_width=4)
    led = ServeLedger()
    bat = ContinuousBatcher(eng, ledger=led)
    reqs = [Request(rid=f"x{i}", prompt=[1 + i] * 12, max_new_tokens=16)
            for i in range(8)]
    for r in reqs:
        bat.submit(r)
    results = bat.run()
    assert len(results) == len(reqs)        # nothing dropped
    shed = [r for r in results.values() if r.status == "shed"]
    done = [r for r in results.values() if r.status == "done"]
    assert shed and done
    assert all(r.reason == "kv_cache_exhausted" for r in shed)
    assert bat.pool.free_pages == small.num_pages - 1
    doc = led.snapshot()
    assert doc["requests"]["shed"] == len(shed)
    assert doc["classes"]["shed"]["ms"] > 0  # metered, not hidden
    assert serve_violations(doc) == []


# ---------------------------------------------------------------------------
# 5. the ledger: exact partition + schema
# ---------------------------------------------------------------------------

def test_ledger_partitions_wall_exactly(eng_fp32, tmp_path):
    led = ServeLedger()
    bat = ContinuousBatcher(eng_fp32, ledger=led)
    for i in range(4):
        bat.submit(Request(rid=f"l{i}", prompt=[3 + i, 1, 4],
                           max_new_tokens=4, seed=i))
    bat.run()
    doc = led.snapshot(olevel="fp32", decode_width=2)
    assert doc["partition_error_us"] == 0
    for row in doc["per_request"]:
        assert sum(row["classes_us"].values()) == row["wall_us"]
    assert doc["requests"] == {"submitted": 4, "served": 4, "shed": 0,
                               "active": 0}
    assert doc["tokens_out"] == 16 and doc["tokens_per_sec"] > 0
    assert serve_violations(doc) == []
    # SERVE.json round-trip (writer validates, atomic replace)
    path = led.write(directory=str(tmp_path), olevel="fp32",
                     decode_width=2)
    assert os.path.basename(path) == sl.ARTIFACT_NAME
    assert serve_violations(sl.load_artifact(path)) == []


def test_serve_violations_flags_broken_docs():
    led = ServeLedger()
    led.submit("a", prompt_len=4)
    led.phase("a", "prefill")
    led.phase("a", "decode")
    led.note_first_token("a")
    led.note_tokens("a", 2)
    led.finish("a")
    doc = led.snapshot()
    assert serve_violations(doc) == []

    bad = dict(doc, kind="goodput_ledger")
    assert any("bad kind" in v for v in serve_violations(bad))
    bad = dict(doc, partition_error_us=3)
    assert any("partition not exact" in v for v in serve_violations(bad))
    bad = dict(doc, olevel="int8")          # int8 without a ratio
    assert any("compression" in v for v in serve_violations(bad))
    bad = dict(doc, requests=dict(doc["requests"], shed=1, served=0))
    assert any("shed" in v for v in serve_violations(bad))
    bad = json.loads(json.dumps(doc))
    bad["per_request"][0]["classes_us"]["decode"] += 5
    assert any("classes sum" in v for v in serve_violations(bad))


def test_ledger_gauges_reach_report_summary(eng_fp32):
    from apex_tpu.telemetry import MemorySink, Registry
    from apex_tpu.telemetry.report import format_summary, summarize
    led = ServeLedger()
    bat = ContinuousBatcher(eng_fp32, ledger=led)
    bat.submit(Request(rid="g", prompt=[2, 4, 6], max_new_tokens=3))
    bat.run()
    sink = MemorySink()
    reg = Registry(sink=sink, flush_interval=0, rank0_only=False)
    led.observe(reg)
    reg.flush()
    s = summarize(sink.records)
    assert s["serve_requests_served"] == 1
    assert s["serve_tokens_per_sec"] > 0
    assert "serving" in format_summary(s)


# ---------------------------------------------------------------------------
# 6. request_flood chaos
# ---------------------------------------------------------------------------

def test_request_flood_grammar():
    plan = faults.parse("request_flood@2:6")
    spec = plan.fire("request_flood", 2)
    assert spec is not None and int(spec.arg) == 6
    with pytest.raises(faults.FaultError):
        faults.parse("request_flood@2:0")       # burst must be >= 1
    with pytest.raises(faults.FaultError):
        faults.parse("request_flood@2:1.5")     # and an integer


def test_request_flood_maps_to_training_badput():
    from apex_tpu.telemetry.goodput import FAULT_BADPUT
    assert FAULT_BADPUT["request_flood"] == "idle"


def test_request_flood_sheds_typed_and_metered(params):
    """The chaos drill: a 6-request burst into a pool that cannot hold
    it.  The engine degrades to typed shedding metered in the ``shed``
    class — no exception, no OOM, no silent drop."""
    # 5 allocatable pages of 4 tokens: four concurrent flood requests
    # (1 page at admission, 2 by the end) oversubscribe the pool
    small = CacheConfig(page_size=4, num_pages=6, max_ctx=32)
    eng = InferenceEngine(params, CFG, cache=small, olevel="bf16",
                          decode_width=4)
    led = ServeLedger()
    bat = ContinuousBatcher(eng, ledger=led)
    bat.submit(Request(rid="real", prompt=[2, 3, 4], max_new_tokens=2))
    faults.install(faults.parse("request_flood@1:6"))
    try:
        results = bat.run()
    finally:
        faults.install(None)
    assert len(results) == 7                 # 1 real + 6 flood, all typed
    assert results["real"].status == "done"
    shed = [r for r in results.values() if r.status == "shed"]
    assert shed and all(r.reason == "kv_cache_exhausted" for r in shed)
    doc = led.snapshot()
    assert doc["requests"]["submitted"] == 7
    assert doc["classes"]["shed"]["ms"] > 0
    assert serve_violations(doc) == []
    assert bat.pool.free_pages == small.num_pages - 1


# ---------------------------------------------------------------------------
# ACCEPTANCE: 32 requests, bf16, concurrent admission/eviction
# ---------------------------------------------------------------------------

def test_acceptance_32_requests_bf16(eng_bf16):
    """ISSUE 18 acceptance: 32 mixed requests through the bf16 engine
    on the CPU mesh with staggered arrivals (admissions and evictions
    interleave across the whole run), every request's output bitwise
    equal to its single-request reference decode, and the ledger's
    classes partitioning every request's wall time exactly."""
    rng = np.random.RandomState(7)
    reqs = [Request(rid=f"a{i}",
                    prompt=[int(t) for t in rng.randint(
                        1, CFG.vocab_size, 3 + int(rng.randint(10)))],
                    max_new_tokens=2 + int(rng.randint(6)),
                    temperature=0.9 if i % 3 == 0 else 0.0,
                    top_k=6 if i % 3 == 0 else 0, seed=100 + i)
            for i in range(32)]
    arrivals = np.cumsum(rng.exponential(0.7, len(reqs))).astype(int)
    led = ServeLedger()
    bat = ContinuousBatcher(eng_bf16, ledger=led)
    i, guard = 0, 0
    while i < len(reqs) or bat.queue or bat.active:
        while i < len(reqs) and arrivals[i] <= bat._step_idx:
            bat.submit(reqs[i])
            i += 1
        bat.step()
        guard += 1
        assert guard < 3000
    results = bat.results
    assert len(results) == 32
    assert all(r.status == "done" for r in results.values())
    assert bat.pool.free_pages == CACHE.num_pages - 1

    # batching/eviction invisibility, against solo reference decodes
    for r in reqs:
        solo = _serve_one(eng_bf16, r)
        assert results[r.rid].tokens == solo.tokens, r.rid

    doc = led.snapshot(olevel="bf16", decode_width=4)
    assert doc["partition_error_us"] == 0
    for row in doc["per_request"]:
        assert sum(row["classes_us"].values()) == row["wall_us"]
    assert doc["requests"]["served"] == 32
    assert serve_violations(doc) == []


# ---------------------------------------------------------------------------
# 7. the perf loop: leg artifact -> audit -> decide -> tuning schema
# ---------------------------------------------------------------------------

def _load_apply():
    spec = importlib.util.spec_from_file_location(
        "apply_perf_results", os.path.join(ROOT, "tools",
                                           "apply_perf_results.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _leg_artifact(eng_fp32):
    """A bench-serve-leg-shaped detail node carrying REAL ledger docs
    (one measured run, snapshotted per variant the way the leg embeds
    them)."""
    led = ServeLedger()
    bat = ContinuousBatcher(eng_fp32, ledger=led)
    for i in range(3):
        bat.submit(Request(rid=f"b{i}", prompt=[4 + i, 2, 9],
                           max_new_tokens=3))
    bat.run()
    def variant(olevel, width, tps, cr=None):
        doc = led.snapshot(olevel=olevel, decode_width=width,
                           compression_ratio=cr)
        return {"olevel": olevel, "decode_width": width,
                "tokens_per_sec": tps, "p50_ms": 2.0, "p99_ms": 4.0,
                "ttft_p50_ms": 1.0, "served": 3, "shed": 0,
                "compression_ratio": cr, "ledger": doc}
    variants = [variant("bf16", 4, 900.0), variant("bf16", 8, 1400.0),
                variant("fp32", 4, 700.0),
                variant("int8", 4, 1100.0, cr=3.5)]
    return {"leg": "serve", "variants": variants,
            "winner": {"olevel": "bf16", "decode_width": 8,
                       "tokens_per_sec": 1400.0}}


def test_serve_leg_audit_and_decide_round_trip(eng_fp32):
    from apex_tpu.utils import tuning
    mod = _load_apply()
    leg = _leg_artifact(eng_fp32)
    artifact = {"backend": "tpu", "detail": {"serve": leg}}
    assert mod.serve_violations(artifact) == []
    prof, rows = mod.decide(artifact, None)
    assert prof["serve_decode_batch"] == 8
    assert prof["serve_olevel"] == "bf16"
    assert tuning.schema_violations(prof) == []
    assert any("serve" in r[0] for r in rows)

    # audit teeth: a winner no variant measured is a violation
    broken = json.loads(json.dumps(leg))
    broken["winner"]["decode_width"] = 16
    assert mod.serve_violations({"serve": broken})
    # ... and decide() must then refuse to persist
    prof2, _ = mod.decide({"backend": "tpu",
                           "detail": {"serve": broken}}, None)
    assert "serve_decode_batch" not in prof2

    # a winner that shed its way to the throughput crown is refused
    shedder = json.loads(json.dumps(leg))
    for v in shedder["variants"]:
        if v["olevel"] == "bf16" and v["decode_width"] == 8:
            v["shed"] = 2
    prof3, _ = mod.decide({"backend": "tpu",
                           "detail": {"serve": shedder}}, None)
    assert "serve_decode_batch" not in prof3


def test_decide_ignores_cpu_measured_serve_leg(eng_fp32):
    mod = _load_apply()
    leg = _leg_artifact(eng_fp32)
    leg["_backend"] = "cpu"
    prof, _ = mod.decide({"backend": "tpu", "detail": {"serve": leg}},
                         None)
    assert "serve_decode_batch" not in prof


@pytest.mark.slow   # ~25s: the full measured serve A/B leg; the decide()
# contract tests above keep the profile gating in tier-1
def test_bench_serve_leg_end_to_end():
    """The real leg: ``bench.bench_serve`` on the CPU mesh — variants
    measured, audit clean, decide() persists a schema-valid profile."""
    from apex_tpu.utils import tuning
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    out = bench.bench_serve(False, n_requests=6)
    assert len(out["variants"]) == 4
    mod = _load_apply()
    artifact = {"backend": "tpu", "detail": {"serve": out}}
    assert mod.serve_violations(artifact) == []
    prof, _rows = mod.decide(artifact, None)
    if "serve_decode_batch" in prof:        # winner may have shed on CPU
        assert tuning.schema_violations(prof) == []
