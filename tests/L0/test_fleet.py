"""``apex_tpu.telemetry.fleet`` (ISSUE 20): per-host run dirs merged
into one writer-validated ``FLEET.json``.

What is proven here:

  * the fleet goodput block: the wall union is the EXACT interval
    union (overlapping host windows counted once, disjoint windows
    summing), the per-class partition is preserved at both levels, and
    a host whose artifact fails its OWN partition fails the merge —
    the fleet view never launders torn books;
  * degradation: any subset of artifacts per host (goodput-only, torn
    JSONL tail, completely empty dir) merges without failing the
    fleet;
  * the 1-host fleet is the degenerate case: its per-host summary IS
    ``report.summarize`` over the same records, exactly;
  * cross-host signals: stragglers are named through
    ``timeline.straggler_rows`` with hosts standing in as devices,
    step-boundary skew comes from the flush timestamps;
  * control decisions and flight dumps correlate across hosts — every
    row names the host that acted/dumped;
  * the N-way Chrome merge: one pid lane group per host, rebased onto
    the shared fleet epoch;
  * THE chaos acceptance: two guard-driven runs (one clean, one under
    ``straggler@N:F`` with the control quarantine) merge into a
    schema-valid FLEET.json whose per-host partitions are exact, whose
    straggler section names the injected host, and whose control
    section carries the acted quarantine;
  * the controller's loss-window signals (``loss.plateau_windows`` /
    ``loss.grad_noise_proxy``) stream as gauges into the per-host
    ``loss`` block.
"""
import calendar
import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.control import ControlConfig, RunController
from apex_tpu.control import ledger as ctl_ledger
from apex_tpu.resilience import GuardConfig, TrainGuard, faults
from apex_tpu.telemetry import JsonlSink, Registry, fleet, goodput
from apex_tpu.telemetry import events as events_mod
from apex_tpu.telemetry import trace as trace_mod
from apex_tpu.telemetry.report import load_records, summarize


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    monkeypatch.delenv("APEX_TPU_METRICS_PORT", raising=False)
    prev_tr = trace_mod.set_tracer(None)
    prev_reg = events_mod.set_default(None)
    prev_led = goodput.install(None)
    prev_plan = faults.install(None)
    yield
    trace_mod.set_tracer(prev_tr)
    events_mod.set_default(prev_reg)
    goodput.install(prev_led)
    faults.install(prev_plan)


# ---------------------------------------------------------------------------
# synthetic run-dir builders
# ---------------------------------------------------------------------------

EPOCH = calendar.timegm(time.strptime("2026-08-07T10:00:00Z",
                                      "%Y-%m-%dT%H:%M:%SZ"))


def _ts_at(epoch):
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(epoch))


def _gdoc(wall_ms, productive_ms, end_epoch, steps=5):
    """A schema-valid GOODPUT.json: productive + idle partition the
    wall exactly, written as of ``end_epoch``."""
    idle_ms = wall_ms - productive_ms
    classes = {}
    for c in fleet.GOODPUT_CLASSES:
        ms = {"productive": productive_ms, "idle": idle_ms}.get(c, 0.0)
        classes[c] = {"ms": round(float(ms), 6),
                      "fraction": round(ms / wall_ms, 6) if wall_ms
                      else 0.0}
    doc = {"kind": "goodput_ledger", "version": 1,
           "ts": _ts_at(end_epoch), "wall_ms": float(wall_ms),
           "goodput_fraction": classes["productive"]["fraction"],
           "classes": classes, "partition_error_ms": 0.0,
           "steps": steps, "replayed_steps": 0,
           "counts": {"rollbacks": 0}, "dropped_intervals": 0}
    assert goodput.goodput_violations(doc) == []
    return doc


def _host_dir(tmp_path, name, gdoc=None, records=None, raw_tail=None):
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    if gdoc is not None:
        (d / "GOODPUT.json").write_text(json.dumps(gdoc))
    if records is not None or raw_tail is not None:
        lines = [json.dumps(r) for r in (records or [])]
        if raw_tail is not None:
            lines.append(raw_tail)          # torn tail: no newline fix-up
        (d / "telemetry.jsonl").write_text("\n".join(lines))
    return str(d)


def _hist(step, mean_ms, epoch):
    return {"kind": "metric", "ts": _ts_at(epoch), "step": int(step),
            "name": "step_time_ms", "type": "histogram",
            "stats": {"count": 1, "sum": float(mean_ms),
                      "min": float(mean_ms), "max": float(mean_ms),
                      "mean": float(mean_ms)}}


# ---------------------------------------------------------------------------
# fleet goodput: union / partition
# ---------------------------------------------------------------------------

def test_overlapping_windows_union_not_double_counted(tmp_path):
    """Two hosts whose 10s walls overlap by 5s: the sum is 20s, the
    union 15s — overlap is never counted twice."""
    a = _host_dir(tmp_path, "a", _gdoc(10_000.0, 8_000.0, EPOCH + 10))
    b = _host_dir(tmp_path, "b", _gdoc(10_000.0, 6_000.0, EPOCH + 15))
    doc, _ = fleet.build_fleet([a, b])
    assert fleet.fleet_violations(doc) == []
    g = doc["goodput"]
    assert g["wall_sum_ms"] == pytest.approx(20_000.0)
    assert g["wall_union_ms"] == pytest.approx(15_000.0)
    assert g["overlap_ms"] == pytest.approx(5_000.0)
    # per-class sums across hosts, fractions over the summed wall
    assert g["classes"]["productive"]["ms"] == pytest.approx(14_000.0)
    assert g["goodput_fraction"] == pytest.approx(0.7)
    assert g["classes"]["idle"]["ms"] == pytest.approx(6_000.0)
    assert doc["n_hosts"] == 2 and doc["hosts"] == ["a", "b"]
    for name in ("a", "b"):
        entry = doc["per_host"][name]
        assert entry["goodput_source"] == "artifact"
        assert entry["partition_ok"] is True


def test_disjoint_windows_union_equals_sum(tmp_path):
    a = _host_dir(tmp_path, "a", _gdoc(10_000.0, 9_000.0, EPOCH + 10))
    b = _host_dir(tmp_path, "b", _gdoc(10_000.0, 9_000.0, EPOCH + 30))
    doc, _ = fleet.build_fleet([a, b])
    g = doc["goodput"]
    assert g["wall_union_ms"] == pytest.approx(g["wall_sum_ms"])
    assert g["overlap_ms"] == pytest.approx(0.0)
    # steps fold across hosts
    assert g["steps"] == 10


def test_torn_host_partition_fails_the_merge(tmp_path):
    """A host artifact whose classes do NOT partition its wall must
    fail the merge — and the auditor must catch the same tear in a
    tampered written doc."""
    good = _gdoc(10_000.0, 8_000.0, EPOCH + 10)
    good["classes"]["productive"]["ms"] += 500.0     # tear the books
    d = tmp_path / "a"
    d.mkdir()
    (d / "GOODPUT.json").write_text(json.dumps(good))
    with pytest.raises(ValueError, match="partition"):
        fleet.build_fleet([str(d)])
    # the read-side auditor catches a post-write tamper too
    a = _host_dir(tmp_path, "b", _gdoc(10_000.0, 8_000.0, EPOCH + 10))
    doc, _ = fleet.build_fleet([a])
    doc["per_host"]["b"]["goodput"]["classes"]["productive"]["ms"] += 500
    assert any("torn" in v or "partition" in v
               for v in fleet.fleet_violations(doc))


def test_fleet_classes_sum_audited(tmp_path):
    a = _host_dir(tmp_path, "a", _gdoc(10_000.0, 8_000.0, EPOCH + 10))
    doc, _ = fleet.build_fleet([a])
    doc["goodput"]["classes"]["idle"]["ms"] += 123.0
    assert any("sum" in v for v in fleet.fleet_violations(doc))
    # union exceeding the sum is double-counted overlap
    doc2, _ = fleet.build_fleet([a])
    doc2["goodput"]["wall_union_ms"] = doc2["goodput"]["wall_sum_ms"] + 9
    assert any("overlap" in v for v in fleet.fleet_violations(doc2))


# ---------------------------------------------------------------------------
# degradation: any subset of artifacts per host
# ---------------------------------------------------------------------------

def test_degraded_hosts_merge_without_failing_the_fleet(tmp_path):
    # host a: goodput artifact only — no JSONL, no summary
    a = _host_dir(tmp_path, "a", _gdoc(5_000.0, 4_000.0, EPOCH + 5))
    # host b: JSONL with a torn tail (killed mid-write) and no ledgers
    b = _host_dir(tmp_path, "b",
                  records=[_hist(2, 10.0, EPOCH + 2)],
                  raw_tail='{"kind": "metric", "ts": "2026-08-0')
    # host c: died before writing anything
    c = tmp_path / "c"
    c.mkdir()
    doc, _ = fleet.build_fleet([a, b, str(c)])
    assert fleet.fleet_violations(doc) == []
    assert doc["n_hosts"] == 3
    pa, pb, pc = (doc["per_host"][h] for h in ("a", "b", "c"))
    assert pa["records"] == 0 and "summary" not in pa
    assert pa["goodput_source"] == "artifact"
    assert pb["records"] == 1                  # the torn line was skipped
    assert pb["window"] is not None            # from the JSONL stamps
    assert pc["records"] == 0 and pc["goodput"] is None
    assert pc["window"] is None
    # only host a contributes wall; the fleet stays consistent
    assert doc["goodput"]["wall_sum_ms"] == pytest.approx(5_000.0)
    # and the rendered table covers every host row
    table = fleet.format_fleet(doc)
    for h in ("a", "b", "c"):
        assert h in table


def test_one_host_fleet_reproduces_report_summarize(tmp_path):
    """The degenerate 1-host fleet must agree with the single-run
    tooling EXACTLY: per_host summary == report.summarize over the
    same records."""
    d = tmp_path / "solo"
    d.mkdir()
    path = d / "telemetry.jsonl"
    reg = Registry(sink=JsonlSink(str(path)), flush_interval=2,
                   rank0_only=False, run_id="solo-run")
    for i in range(4):
        with reg.step():
            reg.gauge("loss").set(2.0 - 0.1 * i)
            reg.counter("examples").add(8)
    reg.event("resumed", step=2)
    reg.close()
    doc, _ = fleet.build_fleet([str(d)])
    assert fleet.fleet_violations(doc) == []
    assert doc["hosts"] == ["solo"]
    expected = summarize(load_records(str(path)))
    assert doc["per_host"]["solo"]["summary"] == expected
    assert doc["per_host"]["solo"]["records"] == len(
        load_records(str(path)))


# ---------------------------------------------------------------------------
# cross-host signals: stragglers + skew
# ---------------------------------------------------------------------------

def test_straggler_names_the_slow_host(tmp_path):
    """4 hosts, one 5x slower on every shared step: the leave-one-out
    z-score (timeline.straggler_rows, hosts as devices) names it."""
    dirs = []
    for h in range(4):
        busy = 50.0 if h == 2 else 10.0
        dirs.append(_host_dir(
            tmp_path, f"h{h}",
            records=[_hist(s, busy, EPOCH + s) for s in (2, 4, 6)]))
    doc, _ = fleet.build_fleet(dirs)
    st = doc["stragglers"]
    assert st["named"] == "h2"
    assert st["max_z"] >= 3.0
    assert st["hosts"] == {"h2": 3}            # flagged on every step
    assert all(r["host"] == "h2" and r["busy_ms"] == 50.0
               for r in st["rows"])
    assert doc["skew"]["steps_compared"] == 3
    # a uniform fleet names nobody
    uni = [_host_dir(tmp_path, f"u{h}",
                     records=[_hist(2, 10.0, EPOCH)]) for h in range(3)]
    doc2, _ = fleet.build_fleet(uni)
    assert doc2["stragglers"]["named"] is None
    assert doc2["stragglers"]["rows"] == []


def test_skew_from_cross_host_flush_timestamps(tmp_path):
    """The same step flushed 2s apart on two hosts reads as 2000ms of
    step-boundary skew."""
    a = _host_dir(tmp_path, "a", records=[_hist(2, 10.0, EPOCH + 1),
                                          _hist(4, 10.0, EPOCH + 2)])
    b = _host_dir(tmp_path, "b", records=[_hist(2, 10.0, EPOCH + 3),
                                          _hist(4, 10.0, EPOCH + 4)])
    doc, _ = fleet.build_fleet([a, b])
    assert doc["skew"]["steps_compared"] == 2
    assert doc["skew"]["max_skew_ms"] == pytest.approx(2_000.0)
    assert doc["skew"]["mean_skew_ms"] == pytest.approx(2_000.0)


# ---------------------------------------------------------------------------
# control + flight correlation
# ---------------------------------------------------------------------------

def _control_doc():
    pol = {"name": "gp_floor", "signal": "goodput_fraction", "lo": 0.5,
           "hi": None, "k_consecutive": 1, "cooldown_windows": 0,
           "action": "comm_retune"}
    rows = [
        {"window": 3, "step": 6, "policy": "gp_floor",
         "signal": "goodput_fraction", "value": 0.3, "lo": 0.5,
         "hi": None, "action": "comm_retune", "outcome": "acted",
         "detail": {"to": "bf16"}},
        {"window": 5, "step": 10, "policy": "gp_floor",
         "signal": "goodput_fraction", "value": 0.2, "lo": 0.5,
         "hi": None, "action": "comm_retune",
         "outcome": "suppressed_cooldown", "detail": {}},
    ]
    return ctl_ledger.build_doc(enabled=True, windows=6, max_actions=2,
                                policies=[pol], decisions=rows,
                                status="completed")


def test_control_decisions_and_flights_carry_their_host(tmp_path):
    a = tmp_path / "a"
    a.mkdir()
    ctl_ledger.write_doc(_control_doc(), directory=str(a))
    b = tmp_path / "b"
    b.mkdir()
    (b / "flight-oom-000012.json").write_text(json.dumps(
        {"reason": "oom", "step": 12, "ts": _ts_at(EPOCH + 7)}))
    (b / "flight-crash-000020.json").write_text('{"reason": "cra')  # torn
    doc, _ = fleet.build_fleet([str(a), str(b)])
    assert fleet.fleet_violations(doc) == []
    ctl = doc["control"]
    assert ctl["actions_fired"] == 1 and ctl["suppressed"] == 1
    assert [d["host"] for d in ctl["decisions"]] == ["a", "a"]
    assert [d["window"] for d in ctl["decisions"]] == [3, 5]  # sorted
    assert doc["per_host"]["a"]["control_decisions"] == 2
    assert doc["per_host"]["b"]["control_decisions"] is None
    flights = doc["flights"]
    assert len(flights) == 2
    by_reason = {f["reason"]: f for f in flights}
    assert by_reason["oom"]["host"] == "b"
    assert by_reason["oom"]["step"] == 12
    assert by_reason["crash"].get("torn") is True    # from the filename
    # a tampered decision row (host stripped) fails the audit
    doc["control"]["decisions"][0].pop("host")
    assert any("host" in v for v in fleet.fleet_violations(doc))


# ---------------------------------------------------------------------------
# the N-way Chrome merge
# ---------------------------------------------------------------------------

def test_merge_host_timelines_lane_groups_and_rebase():
    ev_a = [{"ph": "M", "name": "process_name", "pid": 7,
             "args": {"name": "host"}},
            {"ph": "X", "name": "train.step", "ts": 1000.0, "dur": 50,
             "pid": 7, "tid": 1, "args": {}}]
    ev_b = [{"ph": "X", "name": "train.step", "ts": 400.0, "dur": 60,
             "pid": 7, "tid": 1, "args": {}},
            {"ph": "X", "name": "ckpt.save", "ts": 500.0, "dur": 10,
             "pid": 9, "tid": 1, "args": {}}]
    doc = fleet.merge_host_timelines(
        {"a": ev_a, "b": ev_b}, {"a": 0.0, "b": 2_000.0})
    evs = doc["traceEvents"]
    metas = {e["args"]["name"]: e["pid"] for e in evs if e["ph"] == "M"}
    # one lane group per (host, original pid); names carry the host
    assert set(metas) == {"a:host", "b:pid7", "b:pid9"}
    assert len(set(metas.values())) == 3       # pids never collide
    rows = [e for e in evs if e["ph"] == "X"]
    by = {(e["name"], e["pid"]): e for e in rows}
    # host a's earliest event rebases to its offset (0); host b's to 2000
    assert by[("train.step", metas["a:host"])]["ts"] == pytest.approx(0.0)
    assert by[("train.step", metas["b:pid7"])]["ts"] == pytest.approx(
        2_000.0)
    assert by[("ckpt.save", metas["b:pid9"])]["ts"] == pytest.approx(
        2_100.0)                                # relative spacing kept


# ---------------------------------------------------------------------------
# schema negatives + io/CLI round trip
# ---------------------------------------------------------------------------

def test_fleet_violations_negative_cases(tmp_path):
    assert fleet.fleet_violations([]) != []
    assert any("kind" in v for v in fleet.fleet_violations(
        {"kind": "nope"}))
    a = _host_dir(tmp_path, "a", _gdoc(1_000.0, 900.0, EPOCH + 1))
    doc, _ = fleet.build_fleet([a])
    assert fleet.fleet_violations(doc) == []
    bad = json.loads(json.dumps(doc))
    bad["n_hosts"] = 5
    assert any("n_hosts" in v for v in fleet.fleet_violations(bad))
    bad2 = json.loads(json.dumps(doc))
    bad2["per_host"]["ghost"] = {}
    assert any("per_host" in v for v in fleet.fleet_violations(bad2))
    bad3 = json.loads(json.dumps(doc))
    bad3["goodput"]["goodput_fraction"] = 0.123
    assert any("goodput_fraction" in v
               for v in fleet.fleet_violations(bad3))


def test_write_load_cli_roundtrip(tmp_path, capsys):
    a = _host_dir(tmp_path, "a", _gdoc(10_000.0, 8_000.0, EPOCH + 10))
    b = _host_dir(tmp_path, "b", _gdoc(10_000.0, 7_000.0, EPOCH + 15))
    # host a carries a trace capture -> the merged timeline has events
    (tmp_path / "a" / "run.trace.json").write_text(json.dumps(
        {"traceEvents": [{"ph": "X", "name": "train.step", "ts": 10.0,
                          "dur": 5, "pid": 1, "tid": 1, "args": {}}]}))
    out = tmp_path / "out"
    out.mkdir()
    rc = fleet.cli([a, b, "--out", str(out), "--json"])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "wrote" in printed
    doc = fleet.load_artifact(str(out))        # dir form audits + loads
    assert doc["n_hosts"] == 2
    assert (out / fleet.TIMELINE_NAME).exists()
    tl = json.loads((out / fleet.TIMELINE_NAME).read_text())
    assert any(e.get("ph") == "X" for e in tl["traceEvents"])
    # a single FLEET.json renders without re-merging
    assert fleet.cli([str(out / fleet.ARTIFACT_NAME)]) == 0
    assert "fleet view" in capsys.readouterr().out
    # write_fleet refuses an off-schema doc; the CLI reports bad input
    with pytest.raises(ValueError):
        fleet.write_fleet({"kind": "fleet"}, str(out))
    (tmp_path / "garbage.json").write_text("{not json")
    assert fleet.cli([str(tmp_path / "garbage.json")]) == 1
    # the report CLI dispatches the subcommand
    from apex_tpu.telemetry import report as treport
    assert treport.main(["fleet", str(out / fleet.ARTIFACT_NAME)]) == 0


def test_duplicate_basenames_stay_apart(tmp_path):
    a = tmp_path / "x" / "run"
    b = tmp_path / "y" / "run"
    a.mkdir(parents=True)
    b.mkdir(parents=True)
    (a / "GOODPUT.json").write_text(json.dumps(
        _gdoc(1_000.0, 900.0, EPOCH + 1)))
    (b / "GOODPUT.json").write_text(json.dumps(
        _gdoc(1_000.0, 800.0, EPOCH + 2)))
    doc, _ = fleet.build_fleet([str(a), str(b)])
    assert doc["hosts"] == ["run", "run#2"]
    assert fleet.fleet_violations(doc) == []


# ---------------------------------------------------------------------------
# controller loss-window signals -> per-host loss block (satellite)
# ---------------------------------------------------------------------------

def test_loss_window_signals_flow_into_fleet_loss_block(tmp_path):
    d = tmp_path / "h"
    d.mkdir()
    reg = Registry(sink=JsonlSink(str(d / "telemetry.jsonl")),
                   flush_interval=0, rank0_only=False)
    ctl = RunController(ControlConfig(enabled=True), registry=reg)
    ctl.on_window(step=2, losses=[2.0, 2.2, 1.8])
    rows = ctl.on_window(step=4, losses=[2.0, 2.1, 1.9])  # no improvement
    assert rows == []                          # signals only, no actuator
    reg.close()
    recs = load_records(str(d / "telemetry.jsonl"))
    gz = {r["name"]: r["value"] for r in recs
          if r.get("kind") == "metric" and r.get("type") == "gauge"}
    assert gz["loss.plateau_windows"] == 1.0
    # sample std of [2.0, 2.1, 1.9] over |mean 2.0|
    assert gz["loss.grad_noise_proxy"] == pytest.approx(0.05)
    doc, _ = fleet.build_fleet([str(d)])
    assert doc["per_host"]["h"]["loss"]["loss.plateau_windows"] == 1.0
    assert doc["per_host"]["h"]["loss"][
        "loss.grad_noise_proxy"] == pytest.approx(0.05)


# ---------------------------------------------------------------------------
# THE chaos acceptance (ISSUE 20): two guard runs -> one fleet view
# ---------------------------------------------------------------------------

def _sgd_step():
    @jax.jit
    def step(w, batch):
        g = jax.grad(lambda w: jnp.sum((w - batch) ** 2))(w)
        return w - 0.1 * g, jnp.sum((w - batch) ** 2)
    return step


def _batch_at(i):
    return jnp.asarray(np.random.RandomState(i).randn(4).astype(
        np.float32))


def _guarded_run(run_dir, *, plan=None, controller=None, steps=30):
    """One guard-driven run whose artifacts land in ``run_dir``:
    GOODPUT.json (+ CONTROL.json when a controller acts) via the flight
    destination, and the JSONL gauge stream via a registry whose
    step-time windows are bracketed by the batch callback — each fetch
    closes the previous ``reg.step()`` window, so per-step host timing
    (including an injected straggler's slowdown) streams to disk."""
    os.makedirs(run_dir, exist_ok=True)
    reg = Registry(sink=JsonlSink(os.path.join(run_dir,
                                               "telemetry.jsonl")),
                   flush_interval=2, rank0_only=False,
                   run_id=os.path.basename(run_dir))
    cm_box = [None]

    def batches(i):
        if cm_box[0] is not None:
            cm_box[0].__exit__(None, None, None)
        cm_box[0] = reg.step()
        cm_box[0].__enter__()
        return _batch_at(i)

    tr = trace_mod.Tracer(enabled=True, flight_dir=run_dir)
    prev = trace_mod.set_tracer(tr)
    try:
        cfg = GuardConfig(ckpt_dir=os.path.join(run_dir, "ck"),
                          save_every_steps=4, check_every=2,
                          backoff_seconds=0.01, enabled=True,
                          world_size=8)
        _, rep = TrainGuard(_sgd_step(), cfg, plan=plan, registry=reg,
                            controller=controller).run(
            jnp.zeros(4), batches, steps)
    finally:
        trace_mod.set_tracer(prev)
        reg.close()
    return rep


def test_chaos_two_guard_runs_merge_into_one_fleet_view(tmp_path):
    """Acceptance: a clean guarded run and a straggler+quarantine run
    merge into a schema-valid FLEET.json — per-host goodput classes
    each partition that host's wall EXACTLY, the straggler section
    names the injected host, and the control section carries the acted
    quarantine."""
    clean_dir = str(tmp_path / "clean")
    chaos_dir = str(tmp_path / "chaos")
    rep_clean = _guarded_run(clean_dir)
    assert rep_clean.status == "completed"
    plan = faults.parse("straggler@2x40:10.0")
    ctl = RunController(ControlConfig(enabled=True, max_actions=2))
    rep_chaos = _guarded_run(chaos_dir, plan=plan, controller=ctl)
    assert rep_chaos.status == "preempted"     # the synthesized resize
    assert rep_chaos.resize_to == 7

    doc, timeline = fleet.build_fleet([clean_dir, chaos_dir])
    assert fleet.fleet_violations(doc) == []
    assert doc["hosts"] == ["clean", "chaos"]
    # both hosts' artifacts made it in, each partitioning its own wall
    for h in ("clean", "chaos"):
        entry = doc["per_host"][h]
        assert entry["goodput_source"] == "artifact"
        assert entry["partition_ok"] is True
        good = entry["goodput"]
        total = sum(good["classes"][c]["ms"]
                    for c in fleet.GOODPUT_CLASSES)
        assert abs(total - good["wall_ms"]) <= max(
            1e-3, 1e-6 * good["wall_ms"])
        assert entry["records"] > 0            # the JSONL stream landed
    # the straggler section names the injected host
    st = doc["stragglers"]
    assert doc["skew"]["steps_compared"] >= 2
    assert st["named"] == "chaos", st
    assert st["max_z"] >= 3.0
    # the control section carries the acted quarantine, host-tagged
    q = [d for d in doc["control"]["decisions"]
         if d["action"] == "quarantine" and d["outcome"] == "acted"]
    assert len(q) == 1 and q[0]["host"] == "chaos"
    assert q[0]["detail"]["to_world"] == 7
    assert doc["control"]["actions_fired"] >= 1
    assert doc["per_host"]["clean"]["control_decisions"] is None
    # round trip: write, audit from disk, render
    path = fleet.write_fleet(doc, str(tmp_path / "out"), timeline)
    disk = fleet.load_artifact(path)
    assert disk["stragglers"]["named"] == "chaos"
    assert "quarantine" in fleet.format_fleet(disk)
