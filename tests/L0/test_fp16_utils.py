"""fp16_utils (legacy manual mixed precision) tests — this surface had no
dedicated coverage before round 4.  Mirrors how the reference exercises it:
``tests/L0/run_fp16util/test_fp16util.py`` (network conversion / param
lists) and the FP16_Optimizer flows from the pre-amp docs (backward →
update_master_grads → [clip] → step, plus the closure retry loop)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.fp16_utils import (
    DynamicLossScaler, FP16_Optimizer, LossScaler, convert_network,
    master_params_to_model_params, model_grads_to_master_grads,
    network_to_half, prep_param_lists, tofp16)
from apex_tpu.optimizers import FusedSGD


def _params():
    return {"fc": {"w": jnp.ones((8, 4), jnp.float32),
                   "b": jnp.zeros((4,), jnp.float32)},
            "bn": {"scale": jnp.ones((4,), jnp.float32),
                   "bias": jnp.zeros((4,), jnp.float32)}}


def test_network_conversion_and_bn_safety():
    p = _params()
    half = network_to_half(p)
    assert all(l.dtype == jnp.float16
               for l in jax.tree_util.tree_leaves(half))
    assert tofp16(p)["fc"]["w"].dtype == jnp.float16
    conv = convert_network(p, jnp.float16, keep_batchnorm_fp32=True)
    assert conv["fc"]["w"].dtype == jnp.float16
    # norm-layer params stay fp32 (fp16util.py:60 BN-safety)
    assert conv["bn"]["scale"].dtype == jnp.float32


def test_prep_param_lists_and_copies():
    p = network_to_half(_params())
    model, master = prep_param_lists(p)
    assert all(l.dtype == jnp.float32
               for l in jax.tree_util.tree_leaves(master))
    g32 = model_grads_to_master_grads(
        jax.tree_util.tree_map(lambda x: jnp.ones_like(x), model))
    assert all(l.dtype == jnp.float32
               for l in jax.tree_util.tree_leaves(g32))
    back = master_params_to_model_params(model, master)
    assert back["fc"]["w"].dtype == jnp.float16

    # flat_master packs one fp32 buffer (the apex_C.flatten path)
    model, (fl, flat) = prep_param_lists(p, flat_master=True)
    assert flat.dtype == jnp.float32 and flat.ndim == 1
    back = master_params_to_model_params(model, (fl, flat))
    assert back["fc"]["w"].dtype == jnp.float16
    np.testing.assert_allclose(np.asarray(back["fc"]["w"], np.float32),
                               np.asarray(model["fc"]["w"], np.float32))


def test_loss_scalers_legacy_api_and_defaults():
    s = LossScaler(128.0)
    assert s.loss_scale == 128.0
    assert float(s.backward(jnp.float32(2.0))) == 256.0
    g = s.scale_gradient({"w": jnp.full((4,), 128.0)})
    np.testing.assert_allclose(np.asarray(g["w"]), 1.0)
    s.update_scale(False)                 # static: no-op
    assert s.loss_scale == 128.0

    d = DynamicLossScaler()               # legacy defaults 2**32 / 1000
    assert d.loss_scale == 2.0 ** 32
    assert d.has_overflow({"w": jnp.array([jnp.inf])})
    assert not d.has_overflow({"w": jnp.array([1.0])})
    d.update_scale(True)
    assert d.loss_scale == 2.0 ** 31


def _quadratic_setup(scale=64.0):
    params = {"w": jnp.full((4,), 4.0, jnp.float32)}
    opt = FP16_Optimizer(FusedSGD(lr=0.5), params,
                         static_loss_scale=scale)

    def scaled_grads(masters):
        # d/dw of 0.5*w^2 = w, scaled the way .backward() would
        return jax.tree_util.tree_map(lambda w: w * scale, masters)
    return opt, scaled_grads


def test_fp16_optimizer_one_shot_step_descends():
    opt, sg = _quadratic_setup()
    for _ in range(3):
        opt.step(sg(opt.master_params))
    # w <- w - 0.5*w per step: 4 -> 2 -> 1 -> 0.5
    np.testing.assert_allclose(np.asarray(opt.master_params["w"]), 0.5)
    assert not opt.overflow


def test_fp16_optimizer_staged_flow_with_clip():
    """backward -> update_master_grads -> clip_master_grads -> step(),
    the ported-script flow (reference fp16_optimizer.py:272,417,436)."""
    opt, sg = _quadratic_setup()
    g32 = opt.update_master_grads(sg(opt.master_params))
    np.testing.assert_allclose(np.asarray(g32["w"]), 4.0)   # unscaled
    clipped, norm = opt.clip_master_grads(g32, max_norm=1.0)
    assert float(norm) == pytest.approx(8.0)                 # ||(4,4,4,4)||
    opt.step(grads32=clipped)
    # update = 0.5 * 4/8 = 0.25 per element
    np.testing.assert_allclose(np.asarray(opt.master_params["w"]), 3.75,
                               rtol=1e-6)

    # no-arg step consumes staged grads
    opt.update_master_grads(sg(opt.master_params))
    opt.step()
    np.testing.assert_allclose(np.asarray(opt.master_params["w"]),
                               3.75 / 2, rtol=1e-6)
    with pytest.raises(RuntimeError, match="update_master_grads"):
        opt.step()                                           # nothing staged


def test_fp16_optimizer_overflow_skips_and_halves():
    params = {"w": jnp.ones((4,), jnp.float32)}
    opt = FP16_Optimizer(FusedSGD(lr=0.1), params, dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 2.0 ** 16})
    bad = {"w": jnp.full((4,), jnp.inf)}
    opt.step(bad)
    assert opt.overflow
    np.testing.assert_allclose(np.asarray(opt.master_params["w"]), 1.0)
    assert opt.loss_scale == 2.0 ** 15


def test_fp16_optimizer_closure_retries_until_finite():
    """step(closure): re-evaluates grads after each overflow with the
    halved scale — the reference's _step_with_closure loop."""
    params = {"w": jnp.full((4,), 4.0, jnp.float32)}
    opt = FP16_Optimizer(FusedSGD(lr=0.5), params, dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 2.0 ** 16})
    calls = {"n": 0}

    def closure():
        calls["n"] += 1
        s = opt.loss_scale
        if s > 2.0 ** 14:               # "overflows" until scale drops 2x
            return {"w": jnp.full((4,), jnp.inf)}
        return jax.tree_util.tree_map(lambda w: w * s, opt.master_params)

    opt.step(closure=closure)
    assert calls["n"] == 3              # 2 overflow retries + 1 success
    np.testing.assert_allclose(np.asarray(opt.master_params["w"]), 2.0)

    always_bad = lambda: {"w": jnp.full((4,), jnp.inf)}   # noqa: E731
    with pytest.raises(FloatingPointError, match="20 loss-scale"):
        opt.step(closure=always_bad)


def test_fp16_optimizer_unstaged_grads32_still_guarded():
    """step(grads32=) without a prior update_master_grads must still run
    the finiteness check — no path may write non-finite masters."""
    params = {"w": jnp.ones((4,), jnp.float32)}
    opt = FP16_Optimizer(FusedSGD(lr=0.1), params, dynamic_loss_scale=True)
    opt.step(grads32={"w": jnp.full((4,), jnp.inf)})
    assert opt.overflow
    np.testing.assert_allclose(np.asarray(opt.master_params["w"]), 1.0)


def test_fp16_optimizer_closure_static_scale_skips_not_raises():
    """With a static scaler a retry cannot change the outcome: one
    non-finite evaluation -> skip the step (parity with the non-closure
    paths), not 20 re-evaluations + FloatingPointError."""
    params = {"w": jnp.ones((4,), jnp.float32)}
    opt = FP16_Optimizer(FusedSGD(lr=0.1), params, static_loss_scale=64.0)
    calls = {"n": 0}

    def closure():
        calls["n"] += 1
        return {"w": jnp.full((4,), jnp.inf)}

    opt.step(closure=closure)
    assert calls["n"] == 1 and opt.overflow
    np.testing.assert_allclose(np.asarray(opt.master_params["w"]), 1.0)


def test_fp16_optimizer_one_shot_clears_stale_stage():
    """A one-shot step between update_master_grads and a bare step() must
    drop the stale staged grads (no silent double-apply)."""
    opt, sg = _quadratic_setup()
    opt.update_master_grads(sg(opt.master_params))
    opt.step(sg(opt.master_params))          # one-shot path
    with pytest.raises(RuntimeError, match="update_master_grads"):
        opt.step()                           # stale stage must be gone


def test_fp16_optimizer_state_dict_roundtrip():
    opt, sg = _quadratic_setup()
    opt.step(sg(opt.master_params))
    blob = opt.state_dict()

    opt2, _ = _quadratic_setup()
    opt2.load_state_dict(blob)
    np.testing.assert_allclose(np.asarray(opt2.master_params["w"]),
                               np.asarray(opt.master_params["w"]))
    assert float(opt2.loss_scale) == float(opt.loss_scale)
