"""apex_tpu.telemetry — registry, events, attrib, report (ISSUE 3).

Covers the satellite checklist: counters/histograms fed from ``jax.jit``
outputs on CPU, rank-0 gating, scaler-overflow events across a
forced-inf step, the loader queue-depth gauge, JSONL round-trip through
the SCHEMA validator — plus the acceptance gate: the disabled-mode path
adds NO host sync around the jitted step, and the
``python -m apex_tpu.telemetry`` CLI renders the per-op table and the
step-metrics summary from an instrumented transformer run.
"""
import functools
import json
import os
import subprocess
import sys

import pytest

import jax
import jax.numpy as jnp

from apex_tpu import telemetry
from apex_tpu.telemetry import (JsonlSink, MemorySink, Registry, events,
                                record_violations, records_violations)
from apex_tpu.telemetry import report as treport

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _no_default_registry():
    """Hooks must not leak a default registry between tests."""
    prev = events.set_default(None)
    yield
    events.set_default(prev)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_counters_gauges_histograms_under_jit():
    """Metric updates accept jitted device outputs and aggregate
    correctly once flushed (no value is read before the flush)."""
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    f = jax.jit(lambda x: (x * 2).sum())
    for i in range(3):
        y = f(jnp.ones((4,)) * i)            # device scalar
        reg.counter("total").add(y)
        reg.gauge("last").set(y)
        reg.histogram("h").observe(y)
        reg.counter("n").add(1)
    vals = reg.read()
    assert vals["total"] == pytest.approx(0.0 + 8.0 + 16.0)
    assert vals["last"] == pytest.approx(16.0)
    assert vals["n"] == 3
    recs = reg.flush()
    hist = [r for r in recs if r.get("name") == "h"][0]
    assert hist["stats"]["count"] == 3
    assert hist["stats"]["max"] == pytest.approx(16.0)
    assert records_violations(recs) == []


def test_step_context_batches_host_reads_per_flush_interval(monkeypatch):
    """6 steps at flush_interval=3 -> exactly 2 batched host reads, each
    resolving every pending device value at once."""
    sink = MemorySink()
    reg = Registry(sink=sink, flush_interval=3, rank0_only=False)
    f = jax.jit(lambda x: x + 1)
    gets = []
    real_get = jax.device_get
    monkeypatch.setattr(jax, "device_get",
                        lambda x: gets.append(1) or real_get(x))
    for i in range(6):
        with reg.step():
            y = f(jnp.ones((2,)))
            reg.gauge("loss").set(y.sum())
            reg.counter("examples").add(2)
    assert len(gets) == 2                      # one batched read per flush
    assert len(sink.records) > 0
    steps = [r for r in sink.records if r.get("name") == "step_time_ms"]
    assert sum(r["stats"]["count"] for r in steps) == 6


def test_disabled_mode_is_true_noop_zero_host_syncs(monkeypatch, tmp_path):
    """The acceptance gate: with telemetry disabled, wrapping the jitted
    step adds NO host sync (no block_until_ready, no device_get), stores
    nothing, and never touches the sink."""
    syncs = []
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda x: syncs.append("block") or x)
    monkeypatch.setattr(jax, "device_get",
                        lambda x: syncs.append("get") or x)
    path = tmp_path / "never.jsonl"
    reg = Registry(sink=JsonlSink(str(path)), enabled=False)
    step = jax.jit(lambda x: x * 2)
    for _ in range(4):
        with reg.step():
            y = step(jnp.ones((8,)))
            reg.gauge("loss").set(y)
            reg.counter("examples").add(8)
            reg.histogram("h").observe(y)
            reg.event("e", x=1)
    # observe_scaler with a disabled registry must not device_get either
    from apex_tpu.amp import scaler
    s0 = scaler.init()
    s1 = scaler.update(s0, jnp.asarray(False))
    assert events.observe_scaler(reg, s0, s1) is None
    assert events.observe_scaler(None, s0, s1) is None
    assert reg.flush() == []
    assert syncs == []                         # zero host syncs
    assert reg._metrics == {}                  # nothing stored
    assert not path.exists()                   # sink never opened
    assert reg.counter("a") is telemetry.NULL_METRIC
    # the null metric mirrors the full metric surface (same defaults),
    # so enabled-mode code runs unchanged when telemetry is off
    reg.counter("a").add()
    reg.meter("m").update(3.0)
    assert reg.meter("m").avg == 0.0
    assert str(reg.meter("m")) == "<telemetry disabled>"
    reg.meter("m").reset()


def test_env_var_disables_registry(monkeypatch):
    monkeypatch.setenv("APEX_TPU_TELEMETRY", "0")
    assert Registry().enabled is False
    monkeypatch.setenv("APEX_TPU_TELEMETRY", "1")
    assert Registry().enabled is True
    # explicit argument wins over the env
    monkeypatch.setenv("APEX_TPU_TELEMETRY", "0")
    assert Registry(enabled=True).enabled is True


def test_rank0_gating_single_process(monkeypatch):
    """Off-rank-0 the sink stays silent (aggregation continues); the
    single-process default is rank 0 = emit."""
    from apex_tpu.utils import logging as ulog
    sink = MemorySink()
    reg = Registry(sink=sink, flush_interval=0)
    reg.counter("c").add(1)
    monkeypatch.setattr(ulog, "is_rank0", lambda: False)
    reg.flush()
    assert sink.records == []                  # gated off-rank
    assert reg.read()["c"] == 1                # but still aggregated
    monkeypatch.setattr(ulog, "is_rank0", lambda: True)
    reg.counter("c").add(1)
    reg.flush()
    assert any(r.get("name") == "c" and r["value"] == 2
               for r in sink.records)


def test_meter_behind_registry_and_logging_reexport():
    """AverageMeter/Throughput moved into telemetry.registry; the
    utils.logging import path keeps working, and a registry-attached
    meter lands in the record stream."""
    from apex_tpu.utils.logging import AverageMeter, Throughput
    assert AverageMeter is telemetry.AverageMeter
    assert Throughput is telemetry.Throughput
    m = AverageMeter("loss")
    m.update(2.0)
    m.update(4.0)
    assert m.avg == pytest.approx(3.0)

    sink = MemorySink()
    reg = Registry(sink=sink, flush_interval=0, rank0_only=False)
    reg.meter("speed").update(100.0)
    reg.flush()
    rec = [r for r in sink.records if r.get("name") == "speed"][0]
    assert rec["type"] == "meter" and rec["avg"] == pytest.approx(100.0)
    assert records_violations(sink.records) == []


# ---------------------------------------------------------------------------
# events: scaler transitions, collectives, loader
# ---------------------------------------------------------------------------

def test_scaler_overflow_event_across_forced_inf_step():
    """A forced-inf gradient through the REAL jitted amp pipeline halves
    the scale and emits exactly one amp.overflow event."""
    from apex_tpu import amp
    from apex_tpu.optimizers import FusedSGD
    params = {"w": jnp.ones((4,), jnp.float32)}
    state = amp.initialize(params, FusedSGD(lr=0.1), opt_level="O2",
                           verbosity=0)

    @jax.jit
    def step(state, grads):
        return amp.amp_step(state, grads)

    sink = MemorySink()
    reg = Registry(sink=sink, flush_interval=0, rank0_only=False)
    new = step(state, {"w": jnp.full((4,), jnp.inf, jnp.float16)})
    kinds = events.observe_amp(reg, state, new)
    assert kinds == ["overflow"]
    finite = step(new, {"w": jnp.ones((4,), jnp.float16)})
    assert events.observe_amp(reg, new, finite) == ["steady"]
    reg.flush()
    evs = [r for r in sink.records if r.get("kind") == "event"]
    assert len(evs) == 1 and evs[0]["name"] == "amp.overflow"
    assert evs[0]["fields"]["new_scale"] == pytest.approx(
        evs[0]["fields"]["old_scale"] / 2)
    assert reg.read()["amp.overflow_steps"] == 1
    assert records_violations(sink.records) == []


def test_scaler_growth_event_at_scale_window():
    from apex_tpu.amp import scaler
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    s0 = scaler.init(scale_window=2)
    s1 = scaler.update(s0, jnp.asarray(True))
    assert events.observe_scaler(reg, s0, s1) == "steady"
    s2 = scaler.update(s1, jnp.asarray(True))
    assert events.observe_scaler(reg, s1, s2) == "grew"
    recs = reg.flush()
    ev = [r for r in recs if r.get("kind") == "event"][0]
    assert ev["name"] == "amp.loss_scale_doubled"
    assert ev["fields"]["after_steps"] == 2


def test_transition_kind_clamped_edges():
    from apex_tpu.amp.scaler import transition_kind
    assert transition_kind(8.0, 4.0, 3, 0) == "overflow"
    assert transition_kind(4.0, 8.0, 1999, 0) == "grew"
    assert transition_kind(8.0, 8.0, 5, 6) == "steady"
    # halve clamped at min_loss_scale: only the streak reset shows
    assert transition_kind(1.0, 1.0, 7, 0, scale_window=2000) == "overflow"
    # double clamped at max_loss_scale: window reached, NOT an overflow
    assert transition_kind(2.0 ** 24, 2.0 ** 24, 1999, 0,
                           scale_window=2000) == "steady"
    # with the policy bounds, an overflow at the FLOOR is classified
    # correctly even when the streak happened to sit at window-1 (at the
    # floor a finite window-reached step would have doubled, so an
    # unchanged scale must be an overflow) — code-review finding
    assert transition_kind(1.0, 1.0, 1999, 0, scale_window=2000,
                           min_loss_scale=1.0,
                           max_loss_scale=2.0 ** 24) == "overflow"
    assert transition_kind(2.0 ** 24, 2.0 ** 24, 1999, 0, scale_window=2000,
                           min_loss_scale=1.0,
                           max_loss_scale=2.0 ** 24) == "steady"


def test_observe_scaler_overflow_at_min_scale_window_edge():
    """End-to-end: a scaler pinned at min_loss_scale overflowing on the
    exact window-1 streak still emits amp.overflow (observe_scaler
    passes the state's policy bounds through)."""
    from apex_tpu.amp import scaler
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    s0 = scaler.ScalerState(
        loss_scale=jnp.asarray(1.0, jnp.float32),
        unskipped=jnp.asarray(1, jnp.int32), scale_window=2)
    s1 = scaler.update(s0, jnp.asarray(False))        # overflow at floor
    assert float(s1.loss_scale) == 1.0                # clamped
    assert events.observe_scaler(reg, s0, s1) == "overflow"
    assert reg.read()["amp.overflow_steps"] == 1


def test_collective_meter_records_bytes_and_calls():
    """allreduce_tree reports payload bytes + leaf count into the
    default registry (trace-time semantics documented in events.py)."""
    from jax.sharding import PartitionSpec as P
    from apex_tpu.parallel import create_mesh
    from apex_tpu.parallel.distributed import allreduce_tree
    from apex_tpu.parallel.mesh import shard_map
    mesh = create_mesh({"data": 8})
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def reduce(x):
        return allreduce_tree({"w": x, "b": x})["w"]

    reduce(jnp.arange(8, dtype=jnp.float32))
    vals = reg.read()
    assert vals["ddp.allreduce_calls"] == 1
    # per-shard payload: two f32 leaves of one element each
    assert vals["ddp.allreduce_bytes"] == 8
    assert vals["ddp.allreduce_leaves"] == 2
    recs = reg.flush()
    ev = [r for r in recs if r.get("name") == "ddp.allreduce"][0]
    assert ev["fields"]["axis"] == "data"
    assert records_violations(recs) == []


def test_collective_meter_skips_already_summed_leaves():
    """vma-pre-summed leaves emit no psum, so they must not inflate the
    byte meter (code-review finding): only the varying leaf counts."""
    from jax.sharding import PartitionSpec as P
    from apex_tpu.parallel import create_mesh
    from apex_tpu.parallel.distributed import allreduce_tree
    from apex_tpu.parallel.mesh import shard_map
    mesh = create_mesh({"data": 8})
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)

    @functools.partial(shard_map, mesh=mesh, in_specs=(P("data"), P()),
                       out_specs=P("data"))
    def reduce(x, r):
        out = allreduce_tree({"w": x, "b": r})
        return out["w"] + out["b"]

    reduce(jnp.arange(8, dtype=jnp.float32), jnp.ones((), jnp.float32))
    vals = reg.read()
    if vals.get("ddp.allreduce_leaves") is not None and \
            vals["ddp.allreduce_leaves"] < 2:
        # vma typing active: the replicated leaf was skipped
        assert vals["ddp.allreduce_leaves"] == 1
        assert vals["ddp.allreduce_bytes"] == 4
    else:
        # jax without vma typing psums both leaves — both counted
        assert vals["ddp.allreduce_bytes"] == 8


def test_collective_meter_free_when_no_registry():
    """Without a default registry the hook is inert — allreduce_tree
    still works and nothing is recorded anywhere."""
    from jax.sharding import PartitionSpec as P
    from apex_tpu.parallel import create_mesh
    from apex_tpu.parallel.distributed import allreduce_tree
    from apex_tpu.parallel.mesh import shard_map
    mesh = create_mesh({"data": 8})
    assert events.get_default() is None

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def reduce(x):
        return allreduce_tree({"w": x})["w"]

    out = reduce(jnp.ones(8, jnp.float32))
    assert float(out.sum()) == 8.0


def test_loader_queue_depth_gauge():
    """The python-ring loader reports wait + depth per dequeued batch."""
    from apex_tpu.data.loader import NativeLoader, SyntheticSource
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)
    loader = NativeLoader(SyntheticSource(shape=(4,), n_classes=3),
                          batch_size=2, steps=5, device_put=False)
    batches = list(loader._iter_python())
    assert len(batches) == 5
    vals = reg.read()
    assert vals["loader.queue_depth"] is not None
    assert vals["loader.queue_depth"] >= 0
    # one wait sample per dequeue (incl. the end sentinel)
    assert vals["loader.wait_ms"]["cum_count"] + \
        len(vals["loader.wait_ms"]["window"]) >= 5


# ---------------------------------------------------------------------------
# JSONL round-trip + schema
# ---------------------------------------------------------------------------

def test_jsonl_roundtrip_through_schema_validator(tmp_path):
    path = str(tmp_path / "run.jsonl")
    reg = Registry(sink=JsonlSink(path), flush_interval=2,
                   rank0_only=False, run_id="t")
    for i in range(4):
        with reg.step():
            reg.counter("examples").add(8)
            reg.gauge("loss").set(1.0 / (i + 1))
    reg.event("custom", code=7, note="ok")
    reg.close()
    recs = treport.load_records(path, validate=True)   # raises on drift
    assert records_violations(recs) == []
    assert recs[0]["kind"] == "meta" and recs[0]["run"] == "t"
    summary = treport.summarize(recs)
    assert summary["steps"] == 4
    assert summary["step_time_ms"]["count"] == 4
    assert summary["items_total"] == 32
    text = treport.format_summary(summary)
    assert "step-metrics summary" in text and "overflow events" in text


def test_jsonl_sink_refuses_off_schema_records(tmp_path):
    sink = JsonlSink(str(tmp_path / "x.jsonl"))
    with pytest.raises(ValueError, match="schema"):
        sink.write([{"kind": "metric", "name": "x"}])   # missing fields
    assert not (tmp_path / "x.jsonl").exists()


def test_record_schema_violations():
    good_metric = {"kind": "metric", "ts": "2026-08-04T00:00:00Z",
                   "step": 1, "name": "c", "type": "counter", "value": 2.0}
    assert record_violations(good_metric) == []
    assert record_violations({"kind": "nope"})
    assert record_violations({**good_metric, "mystery": 1})
    assert record_violations({**good_metric, "value": "high"})
    hist = {"kind": "metric", "ts": "t", "step": 0, "name": "h",
            "type": "histogram",
            "stats": {"count": 1, "sum": 1.0, "min": 1.0, "max": 1.0,
                      "mean": 1.0}}
    assert record_violations(hist) == []
    assert record_violations(
        {**hist, "stats": {"count": 1}})       # missing stat keys
    ev = {"kind": "event", "ts": "t", "step": 0, "name": "e",
          "fields": {"a": 1, "b": "x"}}
    assert record_violations(ev) == []
    assert record_violations({**ev, "fields": {"a": [1, 2]}})


def test_load_records_skips_bad_lines_unless_validating(tmp_path):
    p = tmp_path / "r.jsonl"
    good = {"kind": "event", "ts": "t", "step": 0, "name": "e",
            "fields": {}}
    p.write_text(json.dumps(good) + "\n{broken\n"
                 + json.dumps({"kind": "bogus"}) + "\n")
    recs = treport.load_records(str(p))
    assert len(recs) == 1
    with pytest.raises(ValueError):
        treport.load_records(str(p), validate=True)


# ---------------------------------------------------------------------------
# attrib: per-op FLOPs/bytes from the compiled HLO
# ---------------------------------------------------------------------------

def test_attrib_op_table_matmul():
    from apex_tpu.telemetry import attrib

    def f(x, w):
        return jnp.tanh(x @ w).sum()

    table = attrib.op_table(f, jnp.ones((8, 16)), jnp.ones((16, 32)))
    rows = {r["opcode"]: r for r in table["rows"]}
    assert "dot" in rows
    # 2 * M*N*K = 2 * 8*32*16
    assert rows["dot"]["flops"] == pytest.approx(2 * 8 * 32 * 16)
    assert rows["dot"]["bytes"] >= (8 * 16 + 16 * 32 + 8 * 32) * 4
    assert table["total_flops"] > 0
    # joined against the compiler's own cost model (same order)
    assert table["module_flops"] == pytest.approx(table["total_flops"],
                                                  rel=0.5)
    text = attrib.format_op_table(table, top=5)
    assert "per-op cost attribution" in text and "dot" in text


def test_summary_counts_resilience_events():
    """ISSUE 5 satellite: the guard's fault_injected / rollback /
    resumed / preempted events (PR 3) show up in summarize() and the
    rendered summary instead of being dropped."""
    def ev(name, step, **fields):
        return {"kind": "event", "ts": "t", "step": step, "name": name,
                "fields": fields}
    recs = [ev("fault_injected", 5, kind="nan"),
            ev("fault_injected", 6, kind="nan"),
            ev("rollback", 8, to_step=0, attempt=1, reason="streak"),
            ev("resumed", 8),
            ev("preempted", 12),
            ev("sentinel.slow_step", 9, z=5.2)]
    s = treport.summarize(recs)
    assert s["faults_injected"] == 2
    assert s["rollbacks"] == 1
    assert s["resumes"] == 1
    assert s["preemptions"] == 1
    assert s["sentinel_fires"] == 1
    text = treport.format_summary(s)
    assert "resilience" in text
    assert "faults injected 2" in text and "rollbacks 1" in text
    # a clean run stays compact: no resilience line at all
    clean = treport.format_summary(treport.summarize([]))
    assert "resilience" not in clean


def test_guard_run_events_flow_into_cli_summary(tmp_path):
    """End-to-end: a real guard-driven chaos run's registry JSONL
    renders with the resilience counts."""
    import numpy as np
    from apex_tpu.resilience import GuardConfig, TrainGuard, faults

    @jax.jit
    def step(w, batch):
        g = jax.grad(lambda w: jnp.sum((w - batch) ** 2))(w)
        finite = jnp.all(jnp.isfinite(g))
        return jnp.where(finite, w - 0.1 * g, w), jnp.sum((w - batch) ** 2)

    path = str(tmp_path / "guard.jsonl")
    reg = Registry(sink=JsonlSink(path), flush_interval=0, rank0_only=False)
    plan = faults.parse("nan@5x3")
    g = TrainGuard(step, GuardConfig(ckpt_dir=str(tmp_path / "ck"),
                                     save_every_steps=5, check_every=4,
                                     nonfinite_streak=3,
                                     backoff_seconds=0.01, enabled=True),
                   plan=plan, registry=reg)
    batch_at = lambda i: jnp.asarray(
        np.random.RandomState(i).randn(4).astype(np.float32))
    _, rep = g.run(jnp.zeros(4), batch_at, 20)
    assert rep.rollbacks == 1
    reg.close()
    s = treport.summarize(treport.load_records(path, validate=True))
    assert s["faults_injected"] == 3 and s["rollbacks"] == 1
    assert "rollbacks 1" in treport.format_summary(s)


def test_attrib_op_class_rollup():
    """ISSUE 5 satellite (VERDICT missing #7): ops bin into the pyprof
    prof/ class vocabulary and the table carries a per-class rollup."""
    from apex_tpu.telemetry import attrib

    assert attrib.op_class("dot") == "blas"
    assert attrib.op_class("convolution") == "conv"
    assert attrib.op_class("reduce") == "reduction"
    assert attrib.op_class("all-reduce") == "collective"
    assert attrib.op_class("transpose") == "memory"
    assert attrib.op_class("tanh") == "pointwise"
    assert attrib.op_class("custom-call") == "other"

    def f(x, w):
        return jnp.tanh(x @ w).sum()

    table = attrib.op_table(f, jnp.ones((8, 16)), jnp.ones((16, 32)))
    by_class = table["by_class"]
    assert set(by_class) <= set(attrib.OP_CLASSES)
    assert by_class["blas"]["flops"] == pytest.approx(2 * 8 * 32 * 16)
    # pct shares sum to ~100 over the classes present
    assert sum(c["pct_flops"] for c in by_class.values()) == \
        pytest.approx(100.0)
    # every row carries its class
    assert all(r["class"] in attrib.OP_CLASSES for r in table["rows"])
    text = attrib.format_op_table(table, top=5)
    assert "per-class rollup" in text and "blas" in text


def test_attrib_fusion_classified_by_content():
    """A fusion wrapping a reduction is reduction work, not pointwise —
    the fused computation's content decides the class."""
    from apex_tpu.telemetry import attrib
    hlo = """
HloModule m

%fused_reduce (p: f32[64]) -> f32[] {
  %p = f32[64] parameter(0)
  %c = f32[] constant(0)
  ROOT %r = f32[] reduce(f32[64] %p, f32[] %c), dimensions={0}
}

ENTRY %main (x: f32[64]) -> f32[] {
  %x = f32[64] parameter(0)
  ROOT %f = f32[] fusion(f32[64] %x), kind=kInput, calls=%fused_reduce
}
"""
    rows = attrib.parse_hlo(hlo)
    fusion = [r for r in rows if r["opcode"] == "fusion"]
    assert fusion and fusion[0]["class"] == "reduction"
    # a fusion of PURE data movement is memory work, not pointwise
    # (code-review finding: transpose/copy fusions must not launder
    # into the pointwise bucket)
    hlo_mem = hlo.replace(
        "%fused_reduce (p: f32[64]) -> f32[] {\n"
        "  %p = f32[64] parameter(0)\n"
        "  %c = f32[] constant(0)\n"
        "  ROOT %r = f32[] reduce(f32[64] %p, f32[] %c), dimensions={0}\n"
        "}",
        "%fused_reduce (p: f32[64]) -> f32[] {\n"
        "  %p = f32[64] parameter(0)\n"
        "  ROOT %r = f32[] reshape(f32[64] %p)\n"
        "}")
    rows2 = attrib.parse_hlo(hlo_mem)
    fusion2 = [r for r in rows2 if r["opcode"] == "fusion"]
    assert fusion2 and fusion2[0]["class"] == "memory"


def test_attrib_rows_sorted_and_shared_ceilings():
    from apex_tpu.pyprof.prof import HW_CEILINGS
    from apex_tpu.telemetry import attrib

    def f(x):
        return (x @ x.T).mean() + jnp.exp(x).sum()

    table = attrib.op_table(f, jnp.ones((16, 64)))
    flops = [r["flops"] for r in table["rows"]]
    assert flops == sorted(flops, reverse=True)
    ceil = HW_CEILINGS[table["platform"]]
    assert table["peak_flops"] == ceil["peak_flops"]
    for r in table["rows"]:
        assert r["projected_us"] >= 0.0
    # no collectives in a single-device program -> empty sub-table
    assert table["collectives"]["rows"] == []
    assert table["collectives"]["total_logical_bytes"] == 0


def test_attrib_collectives_subtable_logical_bytes():
    """ISSUE 10 satellite: ``op_table`` surfaces per-collective logical
    bytes in a ``collectives`` sub-table, so the planner's comm model
    can be calibrated against what the compiled program actually
    exchanges.  Under shard_map the shapes are per-partition — the
    per-device payload the alpha-beta model predicts."""
    from jax.sharding import PartitionSpec as P
    from apex_tpu.parallel.mesh import create_mesh, shard_map
    from apex_tpu.telemetry import attrib

    n_dev = len(jax.devices())
    mesh = create_mesh({"data": n_dev})
    elems = 2048

    def f(x):
        return jax.lax.psum(x, "data")

    sm = shard_map(f, mesh=mesh, in_specs=(P("data"),),
                   out_specs=P("data"))
    table = attrib.op_table(sm, jnp.ones((n_dev, elems)))
    coll = table["collectives"]
    ar = coll["by_opcode"]["all-reduce"]
    # an all-reduce's logical payload is the per-device buffer, both
    # in and out
    assert ar["logical_bytes"] == elems * 4
    assert ar["in_bytes"] == elems * 4
    assert ar["out_bytes"] == elems * 4
    assert coll["total_logical_bytes"] >= elems * 4
    # the sub-table renders in the formatted output
    assert "per-collective logical bytes" in attrib.format_op_table(table)


# ---------------------------------------------------------------------------
# the CLI acceptance path (subprocess: the real __main__)
# ---------------------------------------------------------------------------

def test_cli_renders_per_op_table_and_step_summary(tmp_path):
    """ISSUE acceptance: ``python -m apex_tpu.telemetry`` renders a
    per-op FLOPs/bytes table plus the step-metrics summary (step time,
    overflow events, collective bytes, loader depth) from a JSONL
    produced by instrumenting the flagship transformer train step on
    CPU — then the written JSONL renders again standalone."""
    out_jsonl = str(tmp_path / "demo.jsonl")
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.telemetry", "--steps", "4",
         "--layers", "1", "--seq", "16", "--batch", "2", "--top", "5",
         "--out", out_jsonl],
        capture_output=True, text=True, cwd=ROOT, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "per-op cost attribution" in r.stdout
    assert "step-metrics summary" in r.stdout
    assert "overflow events     1" in r.stdout       # the forced-inf step
    assert "collective bytes" in r.stdout
    assert "loader wait" in r.stdout
    # the JSONL is schema-valid and renders standalone
    recs = treport.load_records(out_jsonl, validate=True)
    assert records_violations(recs) == []
    r2 = subprocess.run(
        [sys.executable, "-m", "apex_tpu.telemetry", out_jsonl],
        capture_output=True, text=True, cwd=ROOT, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT})
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "step-metrics summary" in r2.stdout


# ---------------------------------------------------------------------------
# report degrade paths (ISSUE 15 satellite): empty / partial / torn-tail
# JSONL streams — the paths existed but were untested
# ---------------------------------------------------------------------------

def test_summarize_empty_stream_renders(tmp_path):
    """An empty (or all-blank) JSONL is a valid degenerate run: zero
    records, a summary full of zeros/Nones, and a render that does not
    crash on any missing field."""
    p = tmp_path / "empty.jsonl"
    p.write_text("")
    recs = treport.load_records(str(p))
    assert recs == []
    s = treport.summarize(recs)
    assert s["steps"] == 0 and s["step_time_ms"] is None
    assert s["loss_scale"] is None and s["goodput_fraction"] is None
    text = treport.format_summary(s)
    assert "step-metrics summary" in text and "n/a" in text
    # blank lines only: same degenerate path
    p.write_text("\n\n   \n")
    assert treport.load_records(str(p)) == []


def test_summarize_partial_stream_events_only(tmp_path):
    """A stream holding ONLY events (a run that died before its first
    metric flush) still summarizes: the resilience line counts them and
    every metric aggregate degrades to its empty default."""
    p = tmp_path / "partial.jsonl"
    recs = [{"kind": "event", "ts": "2026-01-01T00:00:00Z", "step": 3,
             "name": "fault_injected", "fields": {"kind": "nan"}},
            {"kind": "event", "ts": "2026-01-01T00:00:01Z", "step": 4,
             "name": "rollback", "fields": {"to_step": 2}}]
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    s = treport.summarize(treport.load_records(str(p)))
    assert s["faults_injected"] == 1 and s["rollbacks"] == 1
    assert s["steps"] == 4 and s["step_time_ms"] is None
    assert s["collective_bytes"] == 0.0
    text = treport.format_summary(s)
    assert "resilience" in text and "rollbacks 1" in text


def test_load_records_torn_tail_and_off_schema(tmp_path):
    """A writer killed mid-append loses ONLY its torn last line (and
    any off-schema record is skipped, not fatal) — unless the caller
    opts into validate=True, which names the bad line."""
    p = tmp_path / "torn.jsonl"
    good = {"kind": "metric", "ts": "2026-01-01T00:00:00Z", "step": 1,
            "name": "step_time_ms", "type": "histogram",
            "stats": {"count": 1, "sum": 2.0, "min": 2.0, "max": 2.0,
                      "mean": 2.0}, "cum_count": 1}
    off_schema = {"kind": "metric", "ts": "x"}   # missing required keys
    p.write_text(json.dumps(good) + "\n"
                 + json.dumps(off_schema) + "\n"
                 + '{"kind": "metric", "ts": "2026-01-01T00')   # torn
    recs = treport.load_records(str(p))
    assert len(recs) == 1 and recs[0]["name"] == "step_time_ms"
    s = treport.summarize(recs)
    assert s["step_time_ms"]["count"] == 1
    treport.format_summary(s)
    with pytest.raises(ValueError):
        treport.load_records(str(p), validate=True)


def test_summary_goodput_line_folds_next_to_resilience(tmp_path):
    """The goodput line (ISSUE 15): exported ledger gauges in the
    stream render as `goodput fraction ... badput: ...` alongside the
    resilience/memory lines."""
    ts = "2026-01-01T00:00:00Z"
    recs = [
        {"kind": "metric", "ts": ts, "step": 9, "name":
         "goodput.fraction", "type": "gauge", "value": 0.82},
        {"kind": "metric", "ts": ts, "step": 9, "name":
         "badput.data_stall_ms", "type": "gauge", "value": 120.5},
        {"kind": "metric", "ts": ts, "step": 9, "name":
         "badput.recompile_ms", "type": "gauge", "value": 0.0},
        {"kind": "event", "ts": ts, "step": 4, "name": "rollback",
         "fields": {}},
    ]
    assert records_violations(recs) == []
    s = treport.summarize(recs)
    assert s["goodput_fraction"] == pytest.approx(0.82)
    assert s["badput_ms"]["data_stall"] == pytest.approx(120.5)
    text = treport.format_summary(s)
    assert "goodput             fraction 0.820" in text
    assert "data stall 120.5ms" in text
    assert "recompile" not in text        # zero classes stay quiet
