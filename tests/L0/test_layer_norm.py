"""FusedLayerNorm oracle tests — the analog of
tests/L0/run_fused_layer_norm/ (FusedLayerNorm vs torch.nn.LayerNorm
numerics), plus Pallas-vs-XLA path parity (interpret mode on CPU)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torch

from apex_tpu.normalization import (FusedLayerNorm, fused_layer_norm,
                                    fused_layer_norm_affine)


SHAPES = [((4, 16), (16,)), ((2, 3, 33), (33,)), ((5, 128), (128,)),
          ((2, 4, 8), (4, 8))]


@pytest.mark.parametrize("xshape,nshape", SHAPES)
def test_affine_vs_torch(xshape, nshape):
    rng = np.random.RandomState(0)
    x = rng.randn(*xshape).astype(np.float32)
    w = rng.randn(*nshape).astype(np.float32)
    b = rng.randn(*nshape).astype(np.float32)

    out = fused_layer_norm_affine(jnp.asarray(x), jnp.asarray(w),
                                  jnp.asarray(b), nshape)
    tln = torch.nn.LayerNorm(nshape, eps=1e-5)
    with torch.no_grad():
        tln.weight.copy_(torch.tensor(w))
        tln.bias.copy_(torch.tensor(b))
    ref = tln(torch.tensor(x)).detach().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


@pytest.mark.parametrize("xshape,nshape", SHAPES)
@pytest.mark.parametrize("affine", [True, False])
def test_pallas_matches_xla_fwd_bwd(xshape, nshape, affine):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(*xshape).astype(np.float32))
    if affine:
        w = jnp.asarray(rng.randn(*nshape).astype(np.float32))
        b = jnp.asarray(rng.randn(*nshape).astype(np.float32))
    else:
        w = b = None
    g = jnp.asarray(rng.randn(*xshape).astype(np.float32))

    def run(use_pallas):
        def f(x, w, b):
            out = fused_layer_norm_affine(x, w, b, nshape,
                                          use_pallas=use_pallas)
            return jnp.sum(out * g)
        val, grads = jax.value_and_grad(f, argnums=(0,) + (
            (1, 2) if affine else ()))(x, w, b)
        return val, grads

    vx, gx = run(False)
    vp, gp = run(True)
    np.testing.assert_allclose(float(vx), float(vp), rtol=1e-5)
    for a, b2 in zip(jax.tree_util.tree_leaves(gx),
                     jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2), atol=2e-5)


def test_bwd_vs_torch():
    rng = np.random.RandomState(2)
    x = rng.randn(6, 40).astype(np.float32)
    w = rng.randn(40).astype(np.float32)
    b = rng.randn(40).astype(np.float32)

    def f(x_, w_, b_):
        return jnp.sum(fused_layer_norm_affine(x_, w_, b_, (40,)) ** 2)

    dx, dw, db = jax.grad(f, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))

    tx = torch.tensor(x, requires_grad=True)
    tln = torch.nn.LayerNorm((40,), eps=1e-5)
    with torch.no_grad():
        tln.weight.copy_(torch.tensor(w))
        tln.bias.copy_(torch.tensor(b))
    (tln(tx) ** 2).sum().backward()
    np.testing.assert_allclose(np.asarray(dx), tx.grad.numpy(), atol=1e-4)
    np.testing.assert_allclose(np.asarray(dw), tln.weight.grad.numpy(),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(db), tln.bias.grad.numpy(),
                               atol=1e-4)


@pytest.mark.parametrize("use_pallas", [False, True])
def test_module_api_and_bf16(use_pallas):
    ln = FusedLayerNorm(24, use_pallas=use_pallas)
    params = ln.init()
    x = jnp.ones((3, 24), jnp.bfloat16) * 2 + jnp.arange(
        24, dtype=jnp.bfloat16)
    out = ln.apply(params, x)
    assert out.dtype == jnp.bfloat16
    row = np.asarray(out[0], np.float32)
    assert abs(row.mean()) < 0.1 and abs(row.std() - 1.0) < 0.1
    # non-affine
    ln2 = FusedLayerNorm(24, elementwise_affine=False,
                         use_pallas=use_pallas)
    out2 = ln2.apply(ln2.init(), x.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out2).mean(axis=-1), 0.0,
                               atol=1e-5)


def test_jit_and_shape_error():
    ln = FusedLayerNorm((16,), use_pallas=True)
    params = ln.init()
    out = jax.jit(ln.apply)(params, jnp.ones((4, 16)))
    assert out.shape == (4, 16)
    with pytest.raises(ValueError):
        fused_layer_norm(jnp.ones((4, 8)), (16,))
