"""Model zoo smoke + semantics tests (shapes, train/eval BN behavior, grads,
SyncBN-on-mesh parity for the RN50 workload of BASELINE configs 2-3)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import (ResNetConfig, resnet18_config, resnet_init,
                             resnet_apply, DCGANConfig, dcgan_init,
                             generator_apply, discriminator_apply,
                             TransformerConfig, transformer_init,
                             transformer_apply, transformer_loss)


@pytest.fixture(scope="module")
def tiny_rn():
    cfg = resnet18_config(num_classes=10, width=16)
    params, state = resnet_init(jax.random.PRNGKey(0), cfg)
    return cfg, params, state


def test_resnet_shapes_and_state(tiny_rn):
    cfg, params, state = tiny_rn
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits, new_state = resnet_apply(params, state, x, cfg, train=True)
    assert logits.shape == (2, 10)
    # training updates running stats
    a = state["bn_init"]["mean"]
    b = new_state["bn_init"]["mean"]
    assert not np.allclose(np.asarray(a), np.asarray(b))
    # eval keeps them and is deterministic
    l1, s1 = resnet_apply(params, new_state, x, cfg, train=False)
    l2, s2 = resnet_apply(params, new_state, x, cfg, train=False)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    assert s1 is new_state or np.allclose(
        np.asarray(s1["bn_init"]["mean"]),
        np.asarray(new_state["bn_init"]["mean"]))


def test_resnet_grads_finite(tiny_rn):
    cfg, params, state = tiny_rn
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 32, 3))
    y = jnp.array([1, 3])

    def loss(p):
        logits, _ = resnet_apply(p, state, x, cfg, train=True)
        return -jnp.mean(jax.nn.log_softmax(logits)[jnp.arange(2), y])

    g = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_resnet_syncbn_matches_large_batch(tiny_rn):
    """SyncBN over a shard_map'd batch == plain BN on the full batch — the
    two_gpu_unit_test.py oracle, on a CPU device mesh."""
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.parallel.mesh import shard_map

    cfg, params, state = tiny_rn
    n_dev = min(4, len(jax.devices()))
    x = jax.random.normal(jax.random.PRNGKey(3), (2 * n_dev, 32, 32, 3))
    full_logits, full_state = resnet_apply(params, state, x, cfg, train=True)

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))

    @jax.jit
    def sharded(params, state, x):
        def f(x):
            return resnet_apply(params, state, x, cfg, train=True,
                                axis_name="data")
        return shard_map(f, mesh=mesh, in_specs=P("data"),
                         out_specs=(P("data"), P()))(x)

    logits, sh_state = sharded(params, state, x)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full_logits),
                               atol=2e-2, rtol=1e-2)
    np.testing.assert_allclose(
        np.asarray(sh_state["bn_init"]["mean"]),
        np.asarray(full_state["bn_init"]["mean"]), atol=1e-5, rtol=1e-5)


def test_resnet50_param_count():
    cfg = ResNetConfig(num_classes=1000)
    params, _ = resnet_init(jax.random.PRNGKey(0), cfg)
    n = sum(p.size for p in jax.tree_util.tree_leaves(params))
    assert 25_000_000 < n < 26_000_000, n  # torchvision RN50: 25.56M


def test_dcgan_shapes_and_training_signal():
    cfg = DCGANConfig(feat_g=8, feat_d=8)
    params, bstate = dcgan_init(jax.random.PRNGKey(0), cfg)
    z = jax.random.normal(jax.random.PRNGKey(1), (2, cfg.latent_dim))
    img, bstate2 = generator_apply(params, bstate, z, cfg, train=True)
    assert img.shape == (2, 64, 64, 3)
    assert float(jnp.max(jnp.abs(img))) <= 1.0
    logits, _ = discriminator_apply(params, bstate2, img, cfg, train=True)
    assert logits.shape == (2,)

    def d_loss(p):
        out, _ = discriminator_apply(p, bstate2, img, cfg, train=True)
        return jnp.mean(jax.nn.softplus(-out))  # BCE-with-logits, real label

    g = jax.grad(d_loss)(params)
    disc_norm = sum(float(jnp.sum(l ** 2)) for l in
                    jax.tree_util.tree_leaves(g["disc"]))
    assert disc_norm > 0


def test_dcgan_eval_is_batch_composition_independent():
    """Eval-mode BN uses running stats: a fixed z yields the same image
    regardless of batch companions (review finding)."""
    cfg = DCGANConfig(feat_g=8, feat_d=8)
    params, bstate = dcgan_init(jax.random.PRNGKey(0), cfg)
    z0 = jax.random.normal(jax.random.PRNGKey(1), (1, cfg.latent_dim))
    other = jax.random.normal(jax.random.PRNGKey(2), (3, cfg.latent_dim))
    a, _ = generator_apply(params, bstate, z0, cfg, train=False)
    b, _ = generator_apply(params, bstate,
                           jnp.concatenate([z0, other]), cfg, train=False)
    np.testing.assert_allclose(np.asarray(a[0]), np.asarray(b[0]), atol=1e-5)


def test_norm_path_regex_matches_model_bn_names():
    """keep_batchnorm_fp32 must recognize bn1/bn_init/bn_bias paths
    (review finding: \bbn\b fails on them)."""
    from apex_tpu.utils.pytree import convert_network
    cfg = resnet18_config(num_classes=10, width=16)
    params, _ = resnet_init(jax.random.PRNGKey(0), cfg)
    cast = convert_network(params, jnp.bfloat16, keep_batchnorm_fp32=True)
    assert cast["bn_init"]["scale"].dtype == jnp.float32
    assert cast["stage0_block0"]["bn1"]["bn_bias"].dtype == jnp.float32
    assert cast["conv_init"].dtype == jnp.bfloat16


def test_transformer_mask_polarity_nonzero_is_pad():
    """Regression for the round-1 inversion: the key-padding mask uses the
    repo-wide nonzero=PAD polarity (contrib.multihead_attn convention).
    An all-zeros mask must be a no-op; marking positions as pad must (a)
    change other positions' outputs and (b) starve the padded queries'
    attention of real keys only when the REAL keys are marked."""
    cfg = TransformerConfig(vocab_size=64, max_len=32, num_layers=1,
                            d_model=32, num_heads=2, d_ff=64)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    toks = (jnp.arange(16)[None] % 64).astype(jnp.int32)

    o_none = transformer_apply(params, toks, cfg)
    o_zeros = transformer_apply(params, toks, cfg,
                                mask=jnp.zeros((1, 16), jnp.int32))
    np.testing.assert_allclose(np.asarray(o_none), np.asarray(o_zeros),
                               atol=1e-5)

    mask_tail = jnp.zeros((1, 16), jnp.int32).at[0, 8:].set(1)
    o_tail = transformer_apply(params, toks, cfg, mask=mask_tail)
    # masking the tail must change the head's outputs (tail keys dropped)
    assert not np.allclose(np.asarray(o_none[0, :8]),
                           np.asarray(o_tail[0, :8]), atol=1e-5)
    # and the head positions must see ONLY head keys: masking the head
    # instead yields a different result than masking the tail
    mask_head = jnp.zeros((1, 16), jnp.int32).at[0, :8].set(1)
    o_head = transformer_apply(params, toks, cfg, mask=mask_head)
    assert not np.allclose(np.asarray(o_tail), np.asarray(o_head), atol=1e-5)


@pytest.mark.slow   # ~15s: the flash-vs-default numerics oracle at
# model scale; the kernel-level oracles (test_multihead_attn, tpu_smoke
# --tiny) keep the surface in tier-1 (ISSUE 12 budget reclaim)
def test_transformer_fast_attention_matches_default():
    """attn_impl='fast' (contrib flash kernel) must match the jnp oracle
    path in forward AND gradients — the analog of the reference examples
    swapping in fast_self_multihead_attn (self_multihead_attn.py:29).
    Covered: no mask, key-padding mask, causal."""
    import dataclasses as dc
    from apex_tpu.models import transformer_loss
    cfg = TransformerConfig(vocab_size=64, max_len=32, num_layers=2,
                            d_model=64, num_heads=2, d_ff=128)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    toks = (jnp.arange(32)[None] % 64).astype(jnp.int32)
    mask_tail = jnp.zeros((1, 32), jnp.int32).at[0, 24:].set(1)

    for causal, mask in ((False, None), (False, mask_tail), (True, None)):
        c_def = dc.replace(cfg, causal=causal)
        c_fast = dc.replace(cfg, causal=causal, attn_impl="fast")
        o_def = transformer_apply(params, toks, c_def, mask=mask)
        o_fast = transformer_apply(params, toks, c_fast, mask=mask)
        np.testing.assert_allclose(np.asarray(o_fast), np.asarray(o_def),
                                   atol=2e-4, rtol=2e-4)

        batch = {"tokens": toks, "targets": toks, "mask": mask}
        g_def = jax.grad(lambda p: transformer_loss(p, batch, c_def))(params)
        g_fast = jax.grad(lambda p: transformer_loss(p, batch, c_fast))(params)
        for a, b in zip(jax.tree_util.tree_leaves(g_def),
                        jax.tree_util.tree_leaves(g_fast)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-4, rtol=5e-3)

    import pytest
    with pytest.raises(ValueError, match="attn_impl"):
        transformer_apply(params, toks, dc.replace(cfg, attn_impl="nope"))


def test_transformer_remat_same_numerics_less_memory():
    """cfg.remat=True recomputes layer activations in backward: gradients
    identical (same math), backward temp memory strictly smaller for a
    deep model (the jax.checkpoint design goal: trade FLOPs for memory)."""
    from apex_tpu.models import (TransformerConfig, transformer_init,
                                 transformer_loss)

    def make(remat):
        return TransformerConfig(vocab_size=128, max_len=128, num_layers=6,
                                 d_model=64, num_heads=2, d_ff=256,
                                 remat=remat)

    params = transformer_init(jax.random.PRNGKey(0), make(False))
    batch = {"tokens": jnp.ones((2, 128), jnp.int32),
             "targets": jnp.ones((2, 128), jnp.int32)}

    grads = {}
    temp = {}
    for remat in (False, True):
        cfg = make(remat)
        g_fn = jax.grad(lambda p: transformer_loss(p, batch, cfg))
        grads[remat] = g_fn(params)
        compiled = jax.jit(g_fn).lower(params).compile()
        mem = compiled.memory_analysis()
        temp[remat] = int(getattr(mem, "temp_size_in_bytes", 0) or 0)

    for a, b in zip(jax.tree_util.tree_leaves(grads[False]),
                    jax.tree_util.tree_leaves(grads[True])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert 0 < temp[True] < temp[False], temp
