"""Compressed + adaptive collectives (ISSUE 7) on the 8-device CPU mesh.

Covers the tentpole and its acceptance gates:

  * scheme registry / spec grammar / env knob / per-bucket routing;
  * block-scaled int8 quantization bounds and the >=3.5x wire-byte win,
    asserted via the NEW ``ddp.allreduce_compressed_bytes`` counters;
  * error feedback provably tightens vs naive quantization;
  * Adasum pairwise-merge properties vs a numpy oracle;
  * THE A/B: the flagship transformer trained on the CPU mesh with
    ``int8_blockscale`` stays within tolerance of the fp32 run while
    moving >=3.5x fewer wire bytes, per-bucket through the DDP Reducer;
  * ZeRO: compressed reduce-scatter (+ error-feedback residual,
    overflow-revert) and compressed allgather through
    ``DistributedFusedAdam``;
  * resilience: ``collective_fail`` chaos fires through the quantized
    and adasum entry points, and a TrainGuard preempt/resume mid-run
    with residual state in the step carry is bitwise-identical.
"""
import functools
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import (DistributedDataParallel, Reducer,
                               collectives, create_mesh)
from apex_tpu.parallel.distributed import allreduce_tree
from apex_tpu.parallel.mesh import shard_map
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.resilience import faults
from apex_tpu.telemetry import MemorySink, Registry, events
from apex_tpu.telemetry import records_violations
from apex_tpu.utils.pallas import has_vma, _to_varying

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    return create_mesh({"data": N_DEV})


@pytest.fixture(autouse=True)
def _clean_hooks():
    """No leaked default registry, fault plan, or env knob between
    tests."""
    prev_reg = events.set_default(None)
    prev_plan = faults.install(None)
    prev_env = os.environ.pop(collectives.ENV_KNOB, None)
    yield
    events.set_default(prev_reg)
    faults.install(prev_plan)
    os.environ.pop(collectives.ENV_KNOB, None)   # drop test-set values
    if prev_env is not None:
        os.environ[collectives.ENV_KNOB] = prev_env


# ---------------------------------------------------------------------------
# registry / spec / primitives
# ---------------------------------------------------------------------------

def test_registry_names_and_spec_grammar():
    assert set(collectives.available()) >= {"fp32", "bf16",
                                            "int8_blockscale", "adasum"}
    spec = collectives.parse_spec("int8_blockscale:block=64,min_bytes=99")
    assert spec == collectives.CollectiveSpec("int8_blockscale", 64, 99)
    assert collectives.parse_spec("adasum").scheme == "adasum"
    with pytest.raises(collectives.CollectiveError):
        collectives.parse_spec("no_such_scheme")
    with pytest.raises(collectives.CollectiveError):
        collectives.parse_spec("fp32:bogus=1")
    with pytest.raises(collectives.CollectiveError):
        collectives.get_scheme("no_such_scheme")
    # resolve precedence: explicit beats env
    os.environ[collectives.ENV_KNOB] = "bf16"
    assert collectives.resolve("adasum").scheme == "adasum"
    assert collectives.resolve(None).scheme == "bf16"
    os.environ[collectives.ENV_KNOB] = "off"
    assert collectives.resolve(None) is None


def test_wire_bytes_accounting():
    n = 1 << 16
    assert collectives.wire_bytes("fp32", n) == 4 * n
    assert collectives.wire_bytes("bf16", n) == 2 * n
    assert collectives.wire_bytes("adasum", n) == 4 * n
    int8 = collectives.wire_bytes("int8_blockscale", n)
    # 1 B/elem + one fp32 scale per 128-block: >=3.5x under fp32
    assert 4 * n / int8 >= 3.5
    # padding: a partial block still ships whole
    assert collectives.wire_bytes("int8_blockscale", 130, 128) \
        == 2 * 128 + 2 * 4


def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1000).astype(np.float32) * 3.0)
    q, scales = collectives.quantize_blockscale(x, 128)
    assert q.dtype == jnp.int8 and q.shape == (8, 128)
    back = collectives.dequantize_blockscale(q, scales, 1000)
    err = np.abs(np.asarray(back) - np.asarray(x))
    # error <= half a quantization step per block (scale = amax/127)
    bound = np.repeat(np.asarray(scales), 128)[:1000] * 0.5 + 1e-7
    assert (err <= bound).all()
    # all-zero blocks quantize/dequantize to exact zeros
    qz, sz = collectives.quantize_blockscale(jnp.zeros((256,)), 128)
    assert float(jnp.abs(collectives.dequantize_blockscale(
        qz, sz, 256)).max()) == 0.0


def test_adasum_pair_properties():
    rng = np.random.RandomState(1)
    g = jnp.asarray(rng.randn(64).astype(np.float32))
    # parallel gradients -> the mean (a drop-in for averaging)
    np.testing.assert_allclose(np.asarray(collectives.adasum_pair(g, g)),
                               np.asarray(g), rtol=1e-6)
    # orthogonal gradients -> the sum
    a = jnp.asarray([1.0, 0.0]); b = jnp.asarray([0.0, 2.0])
    np.testing.assert_allclose(np.asarray(collectives.adasum_pair(a, b)),
                               [1.0, 2.0], rtol=1e-6)
    # zero-norm side falls back to plain addition
    z = jnp.zeros(2)
    np.testing.assert_allclose(np.asarray(collectives.adasum_pair(a, z)),
                               np.asarray(a), rtol=1e-6)


def _adasum_oracle(stack):
    """Numpy replica of the pairwise tree (same pairing order)."""
    vals = [stack[i].astype(np.float64) for i in range(stack.shape[0])]

    def pair(a, b):
        dot = float(np.vdot(a, b))
        na = float(np.vdot(a, a)); nb = float(np.vdot(b, b))
        ca = 1.0 - dot / (2 * na) if na > 0 else 1.0
        cb = 1.0 - dot / (2 * nb) if nb > 0 else 1.0
        return ca * a + cb * b
    while len(vals) > 1:
        nxt = [pair(vals[i], vals[i + 1])
               for i in range(0, len(vals) - 1, 2)]
        if len(vals) % 2:
            nxt.append(vals[-1])
        vals = nxt
    return vals[0]


def test_adasum_mesh_matches_numpy_oracle(mesh):
    rng = np.random.RandomState(2)
    g = rng.randn(N_DEV, 96).astype(np.float32)

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def red(x):
        return allreduce_tree({"w": x}, scheme="adasum:min_bytes=0")["w"]

    out = np.asarray(red(jnp.asarray(g)))
    expect = _adasum_oracle(g)
    # every device holds the same merged result
    for i in range(N_DEV):
        np.testing.assert_allclose(out[i], expect, rtol=1e-4, atol=1e-5)


def test_custom_scheme_registration(mesh):
    """The pluggability surface: a registered custom scheme routes
    through the same per-bucket selection as the built-ins."""
    info = collectives.SchemeInfo(
        name="_test_negate",
        reduce=lambda x, ax, blk, res: (-jax.lax.psum(x, ax), None),
        wire_bytes=lambda n, b: 4 * n)
    collectives.register_scheme(info)
    try:
        @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"))
        def red(x):
            return allreduce_tree({"w": x}, scheme="_test_negate:min_bytes=0",
                                  average=False)["w"]

        out = red(jnp.ones(N_DEV, jnp.float32))
        np.testing.assert_allclose(np.asarray(out), -8.0)
    finally:
        collectives._REGISTRY.pop("_test_negate")


# ---------------------------------------------------------------------------
# allreduce_tree: schemes, thresholds, metering
# ---------------------------------------------------------------------------

def test_int8_allreduce_close_to_psum(mesh):
    rng = np.random.RandomState(3)
    g = rng.randn(N_DEV, 1024).astype(np.float32)

    def run(scheme):
        @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"))
        def red(x):
            return allreduce_tree({"w": x}, scheme=scheme)["w"]
        return np.asarray(red(jnp.asarray(g)))

    ref = run(None)
    o8 = run("int8_blockscale:min_bytes=0")
    ob = run("bf16:min_bytes=0")
    of = run("fp32")
    np.testing.assert_allclose(of, ref, rtol=1e-6)
    # int8 block-scaled: error bounded by the block quantization step
    assert np.abs(o8 - ref).max() < 0.02 * np.abs(ref).max() + 1e-3
    assert np.abs(ob - ref).max() < 0.05 * np.abs(ref).max() + 1e-2


def test_small_leaves_stay_fp32_and_meter_wire_bytes(mesh):
    """Per-bucket threshold + the NEW compressed-bytes counters: the
    big leaf compresses, the small one stays fp32, and the counters
    carry the exact logical/wire split."""
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("data"), P("data")),
                       out_specs=(P("data"), P("data")))
    def red(big, small):
        out = allreduce_tree(
            {"big": big, "small": small},
            scheme="int8_blockscale:min_bytes=1024")
        return out["big"], out["small"]

    red(jnp.ones((N_DEV, 4096), jnp.float32),
        jnp.ones((N_DEV, 8), jnp.float32))
    vals = reg.read()
    logical = (4096 + 8) * 4
    wire = collectives.wire_bytes("int8_blockscale", 4096) + 8 * 4
    assert vals["ddp.allreduce_bytes"] == logical
    assert vals["ddp.allreduce_compressed_bytes"] == wire
    assert vals["ddp.allreduce_compression_ratio"] == pytest.approx(
        logical / wire)
    assert logical / wire >= 3.5
    recs = reg.flush()
    ev = [r for r in recs if r.get("name") == "ddp.allreduce"][0]
    assert ev["fields"]["wire_bytes"] == wire
    assert ev["fields"]["scheme"] == "int8_blockscale"
    assert ev["fields"]["dtype"] == "mixed"     # int8 big + fp32 small
    assert records_violations(recs) == []


def test_env_knob_selects_scheme(mesh):
    """APEX_TPU_COLLECTIVES compresses a scheme-less allreduce_tree
    call (the A/B-in-one-tunnel-window knob)."""
    os.environ[collectives.ENV_KNOB] = "int8_blockscale:min_bytes=0"
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def red(x):
        return allreduce_tree({"w": x})["w"]

    red(jnp.ones((N_DEV, 512), jnp.float32))
    vals = reg.read()
    assert vals["ddp.allreduce_compressed_bytes"] \
        < vals["ddp.allreduce_bytes"]


def test_per_leaf_callable_routing(mesh):
    """scheme=callable(path, leaf) routes buckets individually."""
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)

    def route(path, leaf):
        return "int8_blockscale:min_bytes=0" if "quantme" in path else None

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("data"), P("data")),
                       out_specs=(P("data"), P("data")))
    def red(a, b):
        out = allreduce_tree({"quantme": a, "keep": b}, scheme=route)
        return out["quantme"], out["keep"]

    red(jnp.ones((N_DEV, 256), jnp.float32),
        jnp.ones((N_DEV, 256), jnp.float32))
    vals = reg.read()
    wire = collectives.wire_bytes("int8_blockscale", 256) + 256 * 4
    assert vals["ddp.allreduce_bytes"] == 2 * 256 * 4
    assert vals["ddp.allreduce_compressed_bytes"] == wire


@pytest.mark.slow   # ~21s: a 12-round constant-grad A/B; the int8+EF
# training path stays in tier-1 via test_ab_flagship_transformer_int8_
# within_tolerance (ISSUE 12 budget reclaim)
def test_error_feedback_tightens_vs_naive(mesh):
    """With a CONSTANT gradient, naive quantization repeats the same
    bias every step; error feedback carries the residual so the running
    mean converges to the true mean — the EF acceptance gate."""
    rng = np.random.RandomState(4)
    g = rng.randn(N_DEV, 512).astype(np.float32)
    true_mean = g.mean(axis=0)
    spec = "int8_blockscale:min_bytes=0"

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def naive(x):
        return allreduce_tree({"w": x}, scheme=spec)["w"]

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("data"), P("data")),
                       out_specs=(P("data"), P("data")))
    def ef(x, r):
        out, nr = allreduce_tree({"w": x}, scheme=spec,
                                 residuals={"w": r})
        return out["w"], nr["w"]

    K = 12
    gj = jnp.asarray(g)
    acc_naive = np.zeros_like(true_mean)
    acc_ef = np.zeros_like(true_mean)
    r = jnp.zeros((N_DEV, 512), jnp.float32)
    for _ in range(K):
        acc_naive += np.asarray(naive(gj))[0]
        out, r = ef(gj, r)
        acc_ef += np.asarray(out)[0]
    err_naive = np.abs(acc_naive / K - true_mean).max()
    err_ef = np.abs(acc_ef / K - true_mean).max()
    assert err_naive > 0
    # EF must beat naive decisively, not within noise
    assert err_ef < 0.5 * err_naive, (err_ef, err_naive)


def test_reducer_threads_scheme(mesh):
    red = Reducer(axis_name="data", collective_scheme="bf16",
                  collective_min_bytes=0)

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def run(x):
        return red.reduce({"w": x})["w"]

    out = run(jnp.full((N_DEV, 16), 2.0, jnp.float32))
    np.testing.assert_allclose(np.asarray(out), 2.0, rtol=1e-2)


def test_noop_outside_mesh_with_residuals():
    ddp = DistributedDataParallel(axis_name="data",
                                  collective_scheme="int8_blockscale")
    g = {"w": jnp.ones((4,))}
    r = ddp.init_residuals(g)
    out, nr = ddp.allreduce_grads(g, residuals=r)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)
    assert nr is r


# ---------------------------------------------------------------------------
# chaos: collective_fail through the new entry points
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("scheme", ["int8_blockscale", "adasum"])
def test_collective_fail_fires_through_schemes(mesh, scheme):
    faults.install(faults.parse("collective_fail@0"))

    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def red(x):
        return allreduce_tree({"w": x},
                              scheme=f"{scheme}:min_bytes=0")["w"]

    with pytest.raises(faults.CollectiveFault):
        red(jnp.ones((N_DEV, 256), jnp.float32))
    # the fault is consumed: the replay traces clean
    faults.install(None)
    out = red(jnp.ones((N_DEV, 256), jnp.float32))
    assert np.isfinite(np.asarray(out)).all()


def test_collective_fail_fires_through_zero_paths():
    faults.install(faults.parse("collective_fail@0x2"))
    opt = DistributedFusedAdam(lr=1e-2, collective_scheme="int8_blockscale")
    params = {"w": jnp.ones((256,), jnp.float32)}
    mesh8 = Mesh(np.array(jax.devices()[:8]), ("data",))

    @functools.partial(shard_map, mesh=mesh8,
                       in_specs=({"w": P()},), out_specs=opt.state_pspecs())
    def init_fn(p):
        return opt.init(p)

    @functools.partial(shard_map, mesh=mesh8,
                       in_specs=(opt.state_pspecs(), {"w": P()},
                                 {"w": P()}),
                       out_specs=({"w": P()}, opt.state_pspecs()),
                       **({} if has_vma() else {"check_vma": False}))
    def step_fn(state, g, p):
        return opt.step(state, g, p)

    state = jax.jit(init_fn)(params)
    with pytest.raises(faults.CollectiveFault):
        jax.jit(step_fn)(state, {"w": jnp.ones((256,))}, params)


# ---------------------------------------------------------------------------
# THE A/B: flagship transformer on the CPU mesh, int8 vs fp32
# ---------------------------------------------------------------------------

def _tiny_cfg():
    from apex_tpu.models import TransformerConfig
    return TransformerConfig(vocab_size=64, max_len=16, num_layers=1,
                             d_model=32, num_heads=2, d_ff=64,
                             dtype=jnp.float32)


def _make_batch(step):
    rng = np.random.RandomState(1000 + step)
    return jnp.asarray(rng.randint(0, 64, (N_DEV, 16)).astype("int32"))


def _transformer_train_fns(mesh, scheme, min_bytes=256):
    """(init_state, jitted step(params, res, tokens) ->
    (params, res, loss)) for the flagship transformer under DDP with
    ``scheme``.  Params stay replicated; grads are taken wrt a
    pcast-varying copy so the reduction actually runs (wrt replicated
    params the cotangent rule pre-sums them and no collective fires);
    the per-device residual rides a stacked leading axis."""
    from apex_tpu.models import transformer_init, transformer_loss
    cfg = _tiny_cfg()
    params0 = transformer_init(jax.random.PRNGKey(0), cfg)
    ddp = DistributedDataParallel(axis_name="data",
                                  collective_scheme=scheme,
                                  collective_min_bytes=min_bytes)
    res0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros((N_DEV,) + jnp.shape(p), jnp.float32), params0)
    pspec = jax.tree_util.tree_map(lambda _: P(), params0)
    rspec = jax.tree_util.tree_map(lambda _: P("data"), params0)
    vma_kw = {} if has_vma() else {"check_vma": False}

    def body(params, res, tokens):
        res = jax.tree_util.tree_map(lambda r: r[0], res)
        pv = jax.tree_util.tree_map(
            lambda p: _to_varying(p, ("data",)), params)
        loss, grads = jax.value_and_grad(lambda p: transformer_loss(
            p, {"tokens": tokens, "targets": tokens}, cfg))(pv)
        grads, res = ddp.allreduce_grads(grads, residuals=res)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - 0.05 * g, params, grads)
        return (new_params,
                jax.tree_util.tree_map(lambda r: r[None], res),
                jax.lax.pmean(loss, "data"))

    step = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(pspec, rspec, P("data")),
        out_specs=(pspec, rspec, P()), **vma_kw))
    return (params0, res0), step


def test_ab_flagship_transformer_int8_within_tolerance(mesh):
    """ACCEPTANCE: N-step CPU-mesh training of the flagship transformer
    with int8_blockscale + error feedback tracks the fp32 run's loss,
    while the compressed-bytes counters prove >=3.5x fewer wire
    bytes."""
    def train(scheme):
        reg = Registry(sink=MemorySink(), flush_interval=0,
                       rank0_only=False)
        prev = events.set_default(reg)
        try:
            (params, res), step = _transformer_train_fns(mesh, scheme)
            losses = []
            for i in range(6):
                params, res, loss = step(params, res, _make_batch(i))
                losses.append(float(loss))
        finally:
            events.set_default(prev)
        vals = reg.read()
        return losses, (vals.get("ddp.allreduce_bytes") or 0,
                        vals.get("ddp.allreduce_compressed_bytes") or 0)

    losses32, (log32, wire32) = train(None)
    losses8, (log8, wire8) = train("int8_blockscale")
    # training happened, and the quantized run tracks fp32
    assert losses32[-1] < losses32[0]
    assert losses8[-1] < losses8[0]
    assert abs(losses8[-1] - losses32[-1]) < 0.05 * abs(losses32[-1]), (
        losses8, losses32)
    # wire-byte proof from the counters: fp32 shipped logical bytes,
    # int8 shipped >=3.5x less
    assert log32 == wire32 > 0
    assert log8 == log32          # same logical payload either way
    assert wire32 / wire8 >= 3.5, (wire32, wire8)


def test_guard_preempt_resume_with_residual_bitwise(mesh, tmp_path):
    """Resilience acceptance: the error-feedback residual rides the
    guard's step-state snapshot — a preempt/resume mid-run ends
    bitwise-identical to an uninterrupted run."""
    from apex_tpu.resilience import GuardConfig, TrainGuard

    (params0, res0), jstep = _transformer_train_fns(
        mesh, "int8_blockscale")

    def step_fn(state, batch):
        params, res = state
        params, res, loss = jstep(params, res, batch)
        return (params, res), loss

    def cfg(d):
        return GuardConfig(ckpt_dir=str(d), save_every_steps=4,
                           check_every=2, backoff_seconds=0.01,
                           enabled=True)

    ref_state, rep = TrainGuard(step_fn, cfg(tmp_path / "ref")).run(
        (params0, res0), _make_batch, 10)
    assert rep.status == "completed"

    plan = faults.parse("preempt@6")
    d = tmp_path / "chaos"
    _, r1 = TrainGuard(step_fn, cfg(d), plan=plan).run(
        (params0, res0), _make_batch, 10)
    assert r1.status == "preempted" and r1.faults_injected == 1
    state2, r2 = TrainGuard(step_fn, cfg(d), plan=plan).run(
        (params0, res0), _make_batch, 10)
    assert r2.status == "completed" and r2.resumed_from is not None

    ref_leaves = jax.tree_util.tree_leaves(ref_state)
    got_leaves = jax.tree_util.tree_leaves(state2)
    assert len(ref_leaves) == len(got_leaves)
    for a, b in zip(ref_leaves, got_leaves):
        assert np.array_equal(np.asarray(a), np.asarray(b))   # bitwise
    # the residual state is genuinely non-trivial (EF is active)
    res_leaves = jax.tree_util.tree_leaves(ref_state[1])
    assert any(float(jnp.abs(r).max()) > 0 for r in res_leaves)


# ---------------------------------------------------------------------------
# ZeRO: compressed reduce-scatter / allgather
# ---------------------------------------------------------------------------

SHAPES = [(33, 7), (128,), (3, 5, 11), (257,)]


def _zero_params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), len(SHAPES))
    return {f"p{i}": jax.random.normal(k, s) * 0.5
            for i, (k, s) in enumerate(zip(ks, SHAPES))}


def _zero_grads(seed, n_dev=N_DEV):
    ks = jax.random.split(jax.random.PRNGKey(seed + 100), len(SHAPES))
    return {f"p{i}": jax.random.normal(k, (n_dev,) + s)
            for i, (k, s) in enumerate(zip(ks, SHAPES))}


def _run_zero(opt, params, iters=3, residual=False, poison_iter=None):
    mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("data",))
    pspec = jax.tree_util.tree_map(lambda _: P(), params)
    gspec = jax.tree_util.tree_map(lambda _: P("data"), params)
    sspec = opt.state_pspecs()
    vma_kw = {} if has_vma() else {"check_vma": False}

    @functools.partial(shard_map, mesh=mesh, in_specs=(pspec,),
                       out_specs=sspec)
    def init_fn(p):
        return opt.init(p)

    @functools.partial(shard_map, mesh=mesh, in_specs=(pspec,),
                       out_specs=P("data"))
    def init_res(p):
        return opt.init_residual(p)[None]

    if residual:
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(sspec, gspec, pspec, P("data")),
                           out_specs=(pspec, sspec, P("data")), **vma_kw)
        def step_fn(state, gl, p, res):
            gl = jax.tree_util.tree_map(lambda g: g[0], gl)
            p2, s2, r2 = opt.step(state, gl, p, residual=res[0])
            return p2, s2, r2[None]
    else:
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(sspec, gspec, pspec),
                           out_specs=(pspec, sspec), **vma_kw)
        def step_fn(state, gl, p):
            gl = jax.tree_util.tree_map(lambda g: g[0], gl)
            return opt.step(state, gl, p)

    state = jax.jit(init_fn)(params)
    res = jax.jit(init_res)(params) if residual else None
    step = jax.jit(step_fn)
    p = params
    for i in range(iters):
        gl = _zero_grads(i)
        if poison_iter is not None and i == poison_iter:
            gl = jax.tree_util.tree_map(
                lambda g: g.at[0].set(jnp.inf), gl)
        if residual:
            p, state, res = step(state, gl, p, res)
        else:
            p, state = step(state, gl, p)
    return p, state, res


def test_zero_int8_reduce_scatter_tracks_fp32():
    params = _zero_params()
    p32, _, _ = _run_zero(DistributedFusedAdam(lr=1e-2), params)
    p8, _, res = _run_zero(
        DistributedFusedAdam(lr=1e-2,
                             collective_scheme="int8_blockscale"),
        params, residual=True)
    for k in p32:
        np.testing.assert_allclose(np.asarray(p32[k]), np.asarray(p8[k]),
                                   atol=3e-2, err_msg=k)
    assert float(jnp.abs(res).max()) > 0      # EF residual is live


def test_zero_adasum_runs_and_stays_finite():
    params = _zero_params()
    pa, state, _ = _run_zero(
        DistributedFusedAdam(lr=1e-2, collective_scheme="adasum"), params)
    for k in pa:
        assert np.isfinite(np.asarray(pa[k])).all()
    assert float(state.gnorm) > 0


def test_zero_allgather_schemes():
    params = _zero_params()
    # "bf16" spec must match the legacy bf16_allgather knob exactly
    p_a, _, _ = _run_zero(
        DistributedFusedAdam(lr=1e-2, bf16_allgather=True), params,
        iters=2)
    p_b, _, _ = _run_zero(
        DistributedFusedAdam(lr=1e-2, allgather_scheme="bf16"), params,
        iters=2)
    for k in p_a:
        np.testing.assert_allclose(np.asarray(p_a[k]), np.asarray(p_b[k]),
                                   atol=0, err_msg=k)
    # int8 allgather: block-quantized params stay near the fp32 gather
    p32, _, _ = _run_zero(DistributedFusedAdam(lr=1e-2), params, iters=2)
    p8, _, _ = _run_zero(
        DistributedFusedAdam(lr=1e-2,
                             allgather_scheme="int8_blockscale"),
        params, iters=2)
    for k in p32:
        np.testing.assert_allclose(np.asarray(p32[k]), np.asarray(p8[k]),
                                   atol=2e-2, err_msg=k)
    # adasum has no allgather meaning
    with pytest.raises(ValueError, match="reduction rule"):
        _run_zero(DistributedFusedAdam(lr=1e-2,
                                       allgather_scheme="adasum"),
                  params, iters=1)


def test_zero_env_knob_reaches_reduce_scatter_not_allgather():
    """APEX_TPU_COLLECTIVES A/Bs the ZeRO gradient reduce-scatter, but
    never implicitly flips the param allgather (quantizing params is a
    deliberate accuracy trade, constructor-arg only — and an ambient
    adasum knob must not crash the gather)."""
    os.environ[collectives.ENV_KNOB] = "adasum"
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)
    params = _zero_params()
    pa, _, _ = _run_zero(DistributedFusedAdam(lr=1e-2), params, iters=1)
    for k in pa:
        assert np.isfinite(np.asarray(pa[k])).all()
    recs = reg.flush()
    evs = {r["name"]: r for r in recs if r.get("kind") == "event"}
    assert evs["zero.reduce_scatter"]["fields"]["scheme"] == "adasum"
    assert evs["zero.allgather"]["fields"].get("scheme") != "adasum"


def test_zero_overflow_reverts_residual():
    """An inf grad skips the step on ALL devices — and must also revert
    the error-feedback residual (the skipped step's quantization error
    was never applied)."""
    params = _zero_params()
    opt = DistributedFusedAdam(lr=1e-2,
                               collective_scheme="int8_blockscale")
    p1, s1, r1 = _run_zero(opt, params, iters=1, residual=True)
    p2, s2, r2 = _run_zero(opt, params, iters=2, residual=True,
                           poison_iter=1)
    assert int(s2.count) == 1
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   atol=0, err_msg=k)
    np.testing.assert_allclose(np.asarray(r1), np.asarray(r2), atol=0)


def test_zero_collectives_metered():
    """The ZeRO reduce-scatter/allgather report through
    record_collective (op=), landing in the zero.* counters and the
    summary's folded collective line."""
    from apex_tpu.telemetry import report as treport
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    events.set_default(reg)
    params = _zero_params()
    _run_zero(DistributedFusedAdam(lr=1e-2,
                                   collective_scheme="int8_blockscale"),
              params, iters=1)
    vals = reg.read()
    assert vals["zero.reduce_scatter_calls"] >= 1
    assert 0 < vals["zero.reduce_scatter_compressed_bytes"] \
        < vals["zero.reduce_scatter_bytes"]
    assert vals["zero.allgather_bytes"] > 0
    recs = reg.flush()
    assert records_violations(recs) == []
    s = treport.summarize(recs)
    assert s["collective_bytes"] > s["collective_wire_bytes"] > 0
    line = treport.format_summary(s)
    assert "logical" in line and "wire" in line


def test_report_summary_uncompressed_line_unchanged():
    """A run with no compression keeps the classic collective line."""
    from apex_tpu.telemetry import report as treport
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    reg.counter("ddp.allreduce_bytes").add(100)
    reg.counter("ddp.allreduce_compressed_bytes").add(100)
    reg.counter("ddp.allreduce_calls").add(1)
    s = treport.summarize(reg.flush())
    assert s["collective_bytes"] == s["collective_wire_bytes"] == 100
    out = treport.format_summary(s)
    assert "collective bytes    100 (1 calls)" in out


def test_bench_collectives_leg_shape():
    """The bench leg: schemes x sizes with the >=3.5x int8 ratio and
    schema-valid embedded telemetry carrying the compressed-bytes
    counters (what apply_perf_results' collective audit checks)."""
    import importlib.util
    ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    leg = bench.bench_collectives(on_tpu=False)
    assert leg["leg"] == "collectives"
    assert set(leg["schemes"]) == {"fp32", "bf16", "int8_blockscale",
                                   "adasum"}
    assert leg["schemes"]["int8_blockscale"]["ratio"] >= 3.5
    assert leg["schemes"]["fp32"]["ratio"] == 1.0
    assert records_violations(leg["telemetry"]["records"]) == []
    names = {r.get("name") for r in leg["telemetry"]["records"]}
    assert "ddp.allreduce_compressed_bytes" in names

    spec2 = importlib.util.spec_from_file_location(
        "apply_perf_results", os.path.join(ROOT, "tools",
                                           "apply_perf_results.py"))
    apr = importlib.util.module_from_spec(spec2)
    spec2.loader.exec_module(apr)
    art = {"backend": "tpu", "detail": {"collectives": leg}}
    assert apr.collective_violations(art) == []
    # the collectives leg is exempt from the MFU/HBM audit (its
    # evidence is bytes, not FLOPs)
    assert apr.perf_field_violations(art) == []
    # a drifted ratio is flagged
    bad = {"backend": "tpu", "detail": {"collectives": {
        "leg": "collectives", "telemetry": leg["telemetry"],
        "schemes": {"int8_blockscale": {"ratio": 2.0}}}}}
    assert any("ratio" in v for v in apr.collective_violations(bad))
