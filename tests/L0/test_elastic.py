"""apex_tpu.elastic (ISSUE 11): topology-adaptive resume across chip
counts on the 8-device CPU mesh.

Covers the tentpole and its acceptance gates:

  * reshard determinism in isolation: N-way -> canonical-flat -> M-way
    -> canonical-flat round-trips BITWISE for several (N, M) pairs
    including non-divisible ones, and EF-residual re-slicing preserves
    the residual sum;
  * MANIFEST meta: world size / plan knobs / flat-shard layout recorded
    by the guard, surfaced by ``load_latest(with_meta=True)``; a
    pre-elastic manifest degrades to same-world-only with a typed
    ``ManifestCompatWarning``, never a KeyError;
  * ``resize@N:M`` in the fault grammar: one-shot like preempt,
    ``skip_until`` honored, target world in ``GuardReport.resize_to``;
  * the latent-hazard fix: an 8-way manifest resumed 4-way WITHOUT
    elastic raises the typed ``WorldSizeMismatchError`` naming both
    counts — loud, not garbage params;
  * THE chaos proof: ``resize@6:4`` kills an 8-way flagship run
    mid-epoch (zero1 update sharding + int8 EF residuals in the step
    carry); the 4-way resume through ``apex_tpu.elastic`` finishes with
    params BITWISE-identical to a clean 4-way run started from the same
    checkpoint, while ``elastic.reshard`` / ``elastic.replan`` events
    land in the registry and ``report.summarize``'s resilience line;
  * the 4 -> 8 grow path at fp32 tolerance (the reshard is exact; the
    wider axis reorders the int8 dequant-sum of the next step);
  * the ``plan.from_tuning`` chips mismatch becoming a re-plan trigger
    once ``elastic.install()`` hooks it.
"""
import functools
import json
import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import apex_tpu.elastic as elastic
from apex_tpu.models import (TransformerConfig, transformer_init,
                             transformer_loss)
from apex_tpu.multi_tensor_apply.flattener import LANE, TreeFlattener
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import collectives, create_mesh
from apex_tpu.parallel import plan as plan_mod
from apex_tpu.parallel import weight_update as wu
from apex_tpu.parallel.mesh import shard_map
from apex_tpu.resilience import (CheckpointManager, GuardConfig,
                                 ManifestCompatWarning, TrainGuard,
                                 WorldSizeMismatchError, faults, guard)
from apex_tpu.telemetry import MemorySink, Registry, events
from apex_tpu.telemetry.report import format_summary, summarize
from apex_tpu.utils.pallas import has_vma, _to_varying

N_DEV = 8
GLOBAL_BATCH = 8
SEQ = 20          # pos-embed 20*32 makes `used` a non-multiple of 1024,
                  # so the 8-way and 4-way canonical totals genuinely
                  # differ (13312 vs 12800) and the re-chunk is real


@pytest.fixture(autouse=True)
def _clean_hooks():
    """No leaked resharder, replan hook, fault plan, or registry."""
    prev_reg = events.set_default(None)
    prev_plan = faults.install(None)
    prev_rs = guard.set_resharder(None)
    prev_hook = plan_mod.set_replan_hook(None)
    yield
    events.set_default(prev_reg)
    faults.install(prev_plan)
    guard.set_resharder(prev_rs)
    plan_mod.set_replan_hook(prev_hook)


# ---------------------------------------------------------------------------
# reshard determinism in isolation (satellite: property tests)
# ---------------------------------------------------------------------------

def _leaves():
    rng = np.random.RandomState(0)
    return {"w": jnp.asarray(rng.randn(33, 7).astype(np.float32)),
            "b": jnp.asarray(rng.randn(130).astype(np.float32)),
            "s": jnp.asarray(rng.randn(1).astype(np.float32))}


@pytest.mark.parametrize("n,m", [(8, 4), (4, 8), (8, 3), (3, 8), (2, 5),
                                 (8, 8)])
def test_rechunk_roundtrip_bitwise(n, m):
    """N-way canonical flat -> M-way -> back is BITWISE: the per-leaf
    content is world-independent, only the chunk padding moves —
    including non-divisible (N, M) pairs."""
    tree = _leaves()
    fl_n = TreeFlattener(tree, chunk=LANE * n)
    fl_m = TreeFlattener(tree, chunk=LANE * m)
    used = int(fl_n.offsets[-1])
    assert used == int(fl_m.offsets[-1])      # offsets are world-free
    flat_n = np.asarray(fl_n.flatten(tree))

    flat_m = collectives.rechunk_flat(flat_n, used=used, total=fl_m.total)
    # every leaf unpacks bitwise from the re-chunked buffer
    got = fl_m.unflatten(jnp.asarray(flat_m))
    for k in tree:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(tree[k]), err_msg=k)
    # and the round trip reproduces the N-way buffer bitwise
    back = collectives.rechunk_flat(flat_m, used=used, total=fl_n.total)
    np.testing.assert_array_equal(back, flat_n)


def test_rechunk_refuses_nonzero_tail():
    buf = np.arange(1, 9, dtype=np.float32)
    with pytest.raises(ValueError, match="nonzero data beyond"):
        collectives.rechunk_flat(buf, used=4, total=16)
    with pytest.raises(ValueError, match="exceeds"):
        collectives.rechunk_flat(buf, used=12, total=16)


@pytest.mark.parametrize("n,m", [(8, 4), (4, 8), (8, 3)])
def test_ef_residual_reslice_preserves_sum(n, m):
    """An EF residual built over the N-way canonical buffer is zero in
    the padding (all-zero blocks quantize with scale 0), so the M-way
    re-slice carries exactly the same residual mass."""
    tree = _leaves()
    fl_n = TreeFlattener(tree, chunk=LANE * n)
    fl_m = TreeFlattener(tree, chunk=LANE * m)
    used = int(fl_n.offsets[-1])
    flat = fl_n.flatten(tree)
    q, scales = collectives.quantize_blockscale(flat, 128)
    res = np.asarray(
        flat - collectives.dequantize_blockscale(q, scales, flat.shape[0]))
    assert np.abs(res).max() > 0              # the residual is live
    assert not np.any(res[used:])             # padding residual is zero
    out = collectives.rechunk_flat(res, used=used, total=fl_m.total)
    # element-identity on the used prefix (zeros elsewhere) IS sum
    # preservation; the f64 check makes it order-independent (a 24-bit
    # mantissa summed 640 times spans < 52 bits — exact in f64)
    np.testing.assert_array_equal(out[:used], res[:used])
    assert not np.any(out[used:])
    assert np.sum(out, dtype=np.float64) == np.sum(res, dtype=np.float64)


def test_layout_meta_contents():
    su = wu.ShardedUpdate(FusedAdam(lr=1e-2, impl="fused"),
                          axis_name="data")
    tree = _leaves()
    meta = su.layout_meta(tree, 8)
    fl = su._fl(tree, 8)
    assert meta["flat_total"] == fl.total and meta["chunk"] == LANE * 8
    assert meta["used"] == int(fl.offsets[-1]) <= fl.total
    per = fl.total // 8
    assert meta["shard_offsets"] == [i * per for i in range(8)]
    assert meta["kind"] == "zero1_flat" and meta["lane"] == LANE


# ---------------------------------------------------------------------------
# stage/expert lattice resharding (ISSUE 17: pp/ep resize@N:M)
# ---------------------------------------------------------------------------

def _lattice_layout(length, rows, lane=LANE):
    """Contiguous-fill row lattice for a canonical flat sequence of
    ``length`` elements: row_total rounded up to whole lanes, full rows
    then one partial tail row — padding only at the global tail (the
    layout the elastic stacked rule reproduces)."""
    per = -(-length // rows)
    row_total = -(-per // lane) * lane
    row_used = [max(min(length - i * row_total, row_total), 0)
                for i in range(rows)]
    return row_total, row_used


def _pack_lattice(flat, rows):
    """(lattice, stacked-block) — the contiguous fill IS the zero-padded
    flat reshaped row-major, so pack/unpack are shape games only."""
    flat = np.asarray(flat)
    row_total, row_used = _lattice_layout(flat.shape[0], rows)
    lat = np.zeros((rows * row_total,), flat.dtype)
    lat[:flat.shape[0]] = flat
    return lat.reshape(rows, row_total), {
        "rows": rows, "row_total": row_total, "row_used": row_used}


def _stacked_meta(world, length, block):
    return {"world_size": world,
            "layout": {"flat_total": block["rows"] * block["row_total"],
                       "used": length, "stacked": dict(block)}}


@pytest.mark.parametrize("n,m", [(2, 4), (4, 2), (2, 3), (3, 2), (8, 3)])
def test_stacked_lattice_reshard_roundtrip_bitwise(n, m):
    """Property: per-stage/per-expert flat lattices re-slice N -> M -> N
    BITWISE through the canonical-flat path, including non-divisible
    row counts (real padding on both sides of the trip)."""
    rng = np.random.RandomState(7)
    flat = rng.randn(1000).astype(np.float32)   # 1000: no lane alignment
    lat_n, blk_n = _pack_lattice(flat, n)
    lat_m_ref, blk_m = _pack_lattice(flat, m)

    tmpl_m = {"lat": jnp.zeros(lat_m_ref.shape, jnp.float32)}
    out = elastic.reshard_payload(
        tmpl_m, {"step": 1, "leaves": [lat_n]},
        _stacked_meta(n, flat.shape[0], blk_n), m)
    got = np.asarray(out["leaves"][0])
    np.testing.assert_array_equal(got, lat_m_ref)

    tmpl_n = {"lat": jnp.zeros(lat_n.shape, jnp.float32)}
    back = elastic.reshard_payload(
        tmpl_n, {"step": 1, "leaves": [got]},
        _stacked_meta(m, flat.shape[0], blk_m), n)
    np.testing.assert_array_equal(np.asarray(back["leaves"][0]), lat_n)


def test_stacked_lattice_int_row_used_and_typed_errors():
    """The scalar ``row_used`` broadcast (every row full), and the
    typed failure modes: a live lattice too small for the content is a
    model change, a nonzero tail beyond ``row_used`` is refused rather
    than silently dropped, and a ``row_used`` arity mismatch names the
    counts."""
    flat = np.arange(1, 513, dtype=np.float32)        # 512 = 4 lanes
    lat, blk = _pack_lattice(flat, 4)
    assert blk["row_used"] == [128] * 4
    meta = _stacked_meta(4, 512, blk)
    meta["layout"]["stacked"]["row_used"] = 128       # int broadcast
    tmpl = {"lat": jnp.zeros((2, 256), jnp.float32)}
    out = elastic.reshard_payload(tmpl, {"step": 0, "leaves": [lat]},
                                  meta, 2)
    np.testing.assert_array_equal(np.asarray(out["leaves"][0]).ravel(),
                                  flat)

    small = {"lat": jnp.zeros((2, 128), jnp.float32)}
    with pytest.raises(WorldSizeMismatchError, match="resize"):
        elastic.reshard_payload(small, {"step": 0, "leaves": [lat]},
                                meta, 2)
    dirty = _stacked_meta(4, 484, dict(blk, row_used=[100, 128, 128, 128]))
    with pytest.raises(WorldSizeMismatchError, match="resize"):
        elastic.reshard_payload(tmpl, {"step": 0, "leaves": [lat]},
                                dirty, 2)
    bad = _stacked_meta(4, 512, dict(blk, row_used=[128, 128]))
    with pytest.raises(WorldSizeMismatchError, match="row_used"):
        elastic.reshard_payload(tmpl, {"step": 0, "leaves": [lat]},
                                bad, 2)


# ---------------------------------------------------------------------------
# manifest meta (satellite: ckpt.py)
# ---------------------------------------------------------------------------

def test_manifest_meta_roundtrip_and_degrade(tmp_path):
    mgr = CheckpointManager(str(tmp_path), meta={"world_size": 8,
                                                 "plan": {"dp": 8}})
    mgr.save(3, {"step": 3, "leaves": [np.zeros(4, np.float32)]})
    assert mgr.manifest_meta()["world_size"] == 8
    found = mgr.load_latest(with_meta=True)
    assert found[0] == 3 and found[2]["plan"] == {"dp": 8}
    # the 2-tuple protocol is unchanged for existing callers
    assert mgr.load_latest()[0] == 3

    # a pre-elastic manifest (no meta) degrades to {} — never KeyError
    doc = json.loads((tmp_path / "MANIFEST.json").read_text())
    doc.pop("meta")
    (tmp_path / "MANIFEST.json").write_text(json.dumps(doc))
    old = CheckpointManager(str(tmp_path))
    assert old.manifest_meta() == {}
    assert old.load_latest(with_meta=True)[2] == {}


# ---------------------------------------------------------------------------
# resize fault grammar (satellite: faults.py)
# ---------------------------------------------------------------------------

def test_resize_fault_grammar():
    assert "resize" in faults.KINDS
    p = faults.parse("resize@40:4;seed=3")
    assert p.specs[0] == faults.FaultSpec(kind="resize", step=40, arg=4.0)
    with pytest.raises(faults.FaultError, match="positive integer"):
        faults.parse("resize@40")
    with pytest.raises(faults.FaultError, match="positive integer"):
        faults.parse("resize@40:0")
    # one-shot: consumed firings never re-fire
    p = faults.parse("resize@6:4")
    assert p.fire("resize", 6) is not None
    assert p.fire("resize", 6) is None
    # skip_until: like preempt, a resize at exactly the resume step
    # already fired in the interrupted run
    p = faults.parse("resize@6:4")
    p.skip_until(6)
    assert p.fire("resize", 6) is None
    p = faults.parse("resize@7:4")
    p.skip_until(6)
    assert p.fire("resize", 7) is not None    # still armed ahead


# ---------------------------------------------------------------------------
# the CPU-mesh harness: flagship transformer, zero1 + int8 EF residual
# ---------------------------------------------------------------------------

def _tiny_cfg():
    return TransformerConfig(vocab_size=64, max_len=SEQ, num_layers=1,
                             d_model=32, num_heads=2, d_ff=64,
                             dtype=jnp.float32)


def _make_batch(step):
    rng = np.random.RandomState(1000 + step)
    return jnp.asarray(
        rng.randint(0, 64, (GLOBAL_BATCH, SEQ)).astype("int32"))


def _build_harness(world):
    """(state0, step_fn, layout) for a ``world``-way zero1 + int8-EF
    DDP training step over the first ``world`` CPU devices.  The GLOBAL
    batch is fixed at 8 rows, so 8-way and 4-way runs see the same data
    stream — the elastic contract."""
    mesh = create_mesh({"data": world}, jax.devices()[:world])
    cfg = _tiny_cfg()
    params0 = transformer_init(jax.random.PRNGKey(0), cfg)
    su = wu.ShardedUpdate(FusedAdam(lr=1e-2, impl="fused"),
                          axis_name="data",
                          collective_scheme="int8_blockscale:min_bytes=0")
    vma_kw = {} if has_vma() else {"check_vma": False}
    pspec = jax.tree_util.tree_map(lambda _: P(), params0)
    sspec = su.state_pspecs(params0, world)

    def grads_of(params, tokens):
        pv = jax.tree_util.tree_map(
            lambda p: _to_varying(p, ("data",)), params)
        return jax.value_and_grad(lambda p: transformer_loss(
            p, {"tokens": tokens, "targets": tokens}, cfg))(pv)

    @functools.partial(shard_map, mesh=mesh, in_specs=(pspec,),
                       out_specs=(sspec, P("data")))
    def init_s(p):
        return su.init(p), su.init_residual(p)[None]

    def body(params, state, res, tokens):
        loss, grads = grads_of(params, tokens)
        params, state, r2 = su.step(state, grads, params, residual=res[0])
        return params, state, r2[None], jax.lax.pmean(loss, "data")

    jstep = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(pspec, sspec, P("data"), P("data")),
        out_specs=(pspec, sspec, P("data"), P()), **vma_kw))
    state0, res0 = jax.jit(init_s)(params0)

    def step_fn(state, batch):
        params, opt_state, res = state
        params, opt_state, res, loss = jstep(params, opt_state, res,
                                             batch)
        return (params, opt_state, res), loss

    return (params0, state0, res0), step_fn, su.layout_meta(params0, world)


@pytest.fixture(scope="module")
def harnesses():
    return {w: _build_harness(w) for w in (8, 4)}


def _gcfg(d, world, layout, **kw):
    return GuardConfig(ckpt_dir=str(d), save_every_steps=2, check_every=2,
                       backoff_seconds=0.01, enabled=True,
                       world_size=world,
                       ckpt_meta={"plan": {"dp": world},
                                  "layout": layout}, **kw)


def _import_canonical(template_state, payload, saved_world, layout):
    """The INDEPENDENT canonical-flat import the comparator run uses:
    inline numpy re-chunk + replica-0 residual collapse, no elastic
    code — what 'a clean run started from the same checkpoint' means."""
    used, tot = int(layout["used"]), int(layout["flat_total"])
    tmpl_leaves, treedef = jax.tree_util.tree_flatten(template_state)
    out = []
    for t, h in zip(tmpl_leaves, payload["leaves"]):
        h = np.asarray(h)
        if h.shape == tuple(t.shape):
            v = h
        elif h.ndim == 1 and h.shape[0] == tot:
            assert not np.any(h[used:])
            v = np.zeros((t.shape[0],), h.dtype)
            v[:used] = h[:used]
        elif h.ndim == 2 and h.shape == (saved_world, tot):
            acc = np.zeros((t.shape[1],), h.dtype)
            for row in h:
                r = np.zeros((t.shape[1],), h.dtype)
                r[:used] = row[:used]
                acc = acc + r
            v = np.zeros(tuple(t.shape), h.dtype)
            v[0] = acc
        else:
            raise AssertionError((h.shape, tuple(t.shape)))
        sh = t.sharding if isinstance(t.sharding, NamedSharding) else None
        out.append(jax.device_put(v.astype(t.dtype), sh))
    return jax.tree_util.tree_unflatten(treedef, out)


def _tiny_profile():
    """A hand-built cost-model profile (test_plan's oracle idiom) so the
    re-plan search runs without an AOT compile."""
    return plan_mod.ModelProfile(
        name="tiny", flops=1e9, bytes_accessed=1e8,
        params_bytes=1 << 20, optimizer_bytes=3 << 20,
        activations_bytes=1 << 20, batch_bytes=1 << 16,
        temps_bytes=1 << 18, output_bytes=1 << 10, platform="cpu")


# ---------------------------------------------------------------------------
# the latent-hazard fix + THE chaos proof
# ---------------------------------------------------------------------------

def test_chaos_resize_8_to_4_bitwise(harnesses, tmp_path):
    """ACCEPTANCE: resize@6:4 kills the 8-way zero1+int8-EF run
    mid-epoch; WITHOUT elastic the 4-way resume raises the typed
    WorldSizeMismatchError naming both counts; WITH elastic it
    reshards, replans, and finishes BITWISE-identical to a clean 4-way
    run started from the same checkpoint."""
    state8, step8, layout8 = harnesses[8]
    state4, step4, layout4 = harnesses[4]
    d = tmp_path / "ckpts"

    plan = faults.parse("resize@6:4")
    _, r1 = TrainGuard(step8, _gcfg(d, 8, layout8), plan=plan).run(
        state8, _make_batch, 10)
    assert r1.status == "preempted" and r1.final_step == 6
    assert r1.resize_to == 4 and r1.faults_injected == 1

    # the latent hazard, fixed: a 4-way resume of the 8-way manifest
    # without elastic is a LOUD typed error, not garbage params
    with pytest.raises(WorldSizeMismatchError,
                       match="world size 8.*world size 4") as ei:
        TrainGuard(step4, _gcfg(d, 4, layout4), plan=plan).run(
            state4, _make_batch, 10)
    assert ei.value.saved_world == 8 and ei.value.live_world == 4

    # the clean comparator: import the SAME checkpoint into 4-way
    # shapes independently and run the remaining steps plain
    ck_step, payload, meta = CheckpointManager(str(d)).load_latest(
        with_meta=True)
    assert ck_step == 6 and meta["world_size"] == 8
    assert meta["plan"] == {"dp": 8}
    assert meta["layout"]["flat_total"] == layout8["flat_total"]
    assert layout8["flat_total"] != layout4["flat_total"]   # real re-chunk
    state_b = _import_canonical(state4, payload, 8, meta["layout"])
    for i in range(ck_step, 10):
        state_b, _ = step4(state_b, _make_batch(i))

    # the elastic resume: reshard + replan + continue, metered
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False)
    er = elastic.ElasticResume(profile=_tiny_profile())
    state_a, r2 = TrainGuard(step4, _gcfg(d, 4, layout4), plan=plan,
                             registry=reg, elastic=er).run(
        state4, _make_batch, 10)
    assert r2.status == "completed" and r2.final_step == 10
    assert r2.resumed_from == 6 and r2.resharded_from == 8

    # BITWISE: params and the full carry (opt state + EF residual)
    for a, b in zip(jax.tree_util.tree_leaves(state_a),
                    jax.tree_util.tree_leaves(state_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(state_a[1].count) == 10        # the update really ran
    assert float(jnp.abs(state_a[2]).max()) > 0   # EF residual live

    # the replan ran for the NEW chip count
    assert er.last_plan is not None and er.last_plan.chips == 4

    # events: elastic.reshard + elastic.replan through the pinned
    # registry, folded into the report's resilience line
    recs = reg.flush()
    evs = {r["name"]: r for r in recs if r.get("kind") == "event"}
    assert evs["elastic.reshard"]["fields"]["from_world"] == 8
    assert evs["elastic.reshard"]["fields"]["to_world"] == 4
    assert evs["elastic.reshard"]["fields"]["fields_resharded"] >= 4
    assert evs["elastic.replan"]["fields"]["chips"] == 4
    assert evs["elastic.replan"]["fields"]["new_knobs"]["dp"] == 4
    summary = summarize(recs)
    assert summary["reshards"] == 1 and summary["replans"] == 1
    text = format_summary(summary)
    assert "reshards 1" in text and "replans 1" in text


@pytest.mark.slow   # the grow direction re-runs both harnesses' guard
def test_grow_4_to_8_fp32_tolerance(harnesses, tmp_path):
    """The reverse path: a 4-way run resized to 8 chips resumes through
    the same reshard.  The elastic resume is BITWISE the independent
    canonical import continued 8-way (the machinery adds nothing), and
    matches the would-have-been 4-way continuation only at fp32
    tolerance — the wider axis changes which local grads each replica
    quantizes, so the int8 EF noise differs (the documented grow-path
    caveat)."""
    state8, step8, layout8 = harnesses[8]
    state4, step4, layout4 = harnesses[4]
    d = tmp_path / "grow"

    plan = faults.parse("resize@5:8")
    _, r1 = TrainGuard(step4, _gcfg(d, 4, layout4), plan=plan).run(
        state4, _make_batch, 10)
    assert r1.status == "preempted" and r1.resize_to == 8

    ck_step, payload, meta = CheckpointManager(str(d)).load_latest(
        with_meta=True)
    assert ck_step == 5 and meta["world_size"] == 4

    er = elastic.ElasticResume()
    state_a, r2 = TrainGuard(step8, _gcfg(d, 8, layout8), plan=plan,
                             elastic=er).run(state8, _make_batch, 10)
    assert r2.status == "completed" and r2.resharded_from == 4

    # (a) bitwise vs the independent 8-way canonical import
    state_c = _import_canonical(state8, payload, 4, meta["layout"])
    for i in range(ck_step, 10):
        state_c, _ = step8(state_c, _make_batch(i))
    for (kp, a), (_, c) in zip(
            jax.tree_util.tree_leaves_with_path(state_a[0]),
            jax.tree_util.tree_leaves_with_path(state_c[0])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c),
                                      err_msg=str(kp))

    # (b) tolerance vs the clean 4-way continuation: same data, same
    # math, but 8 replicas quantize different local grad buffers than
    # 4 did, so the int8+EF noise differs — the documented caveat.
    # Adam normalization amplifies that noise on near-zero params, so
    # the bound is absolute-dominated (empirically ~1e-2 after 5 steps)
    state_d = _import_canonical(state4, payload, 4, meta["layout"])
    for i in range(ck_step, 10):
        state_d, _ = step4(state_d, _make_batch(i))
    for (kp, a), (_, dd) in zip(
            jax.tree_util.tree_leaves_with_path(state_a[0]),
            jax.tree_util.tree_leaves_with_path(state_d[0])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(dd),
                                   rtol=0.25, atol=2e-2,
                                   err_msg=str(kp))


def _moe_lattice_harness(rows):
    """An ep-flagship training step whose per-expert FFN weights live
    in a ``(rows, row_total)`` stacked flat lattice — the storage
    layout an expert-sharded run checkpoints, and exactly what the
    elastic stacked rule reshards across widths.  The step itself is
    world-independent (unpack -> same params -> same SGD update), so a
    resized resume must continue BITWISE."""
    from apex_tpu.models.moe_transformer import (MoETransformerConfig,
                                                 moe_transformer_init,
                                                 moe_transformer_loss)
    cfg = MoETransformerConfig(vocab_size=64, max_len=8, num_layers=1,
                               d_model=16, num_heads=2, d_ff=32,
                               num_experts=8)
    full0 = moe_transformer_init(jax.random.PRNGKey(0), cfg)
    shapes = [(l["w_in"].shape, l["w_out"].shape)
              for l in full0["layers"]]
    canon = sum(int(np.prod(si)) + int(np.prod(so))
                for si, so in shapes)
    row_total, row_used = _lattice_layout(canon, rows)

    def split(full):
        pieces, layers = [], []
        for l in full["layers"]:
            l = dict(l)
            pieces.append(l.pop("w_in").ravel())
            pieces.append(l.pop("w_out").ravel())
            layers.append(l)
        flat = jnp.concatenate(pieces)
        lat = jnp.zeros((rows * row_total,), flat.dtype)
        return ({**full, "layers": layers},
                lat.at[:canon].set(flat).reshape(rows, row_total))

    def join(dense, lat):
        flat = lat.reshape(-1)[:canon]
        off, layers = 0, []
        for l, (si, so) in zip(dense["layers"], shapes):
            ni, no = int(np.prod(si)), int(np.prod(so))
            layers.append({**l,
                           "w_in": flat[off:off + ni].reshape(si),
                           "w_out": flat[off + ni:off + ni + no]
                           .reshape(so)})
            off += ni + no
        return {**dense, "layers": layers}

    lr = 0.05

    @jax.jit
    def jstep(dense, lat, tokens):
        def loss_fn(dn, lt):
            return moe_transformer_loss(
                join(dn, lt), {"tokens": tokens, "targets": tokens}, cfg)
        loss, (gd, gl) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(dense, lat)
        dense = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                       dense, gd)
        # the lattice padding gets exact-zero grads (the loss reads
        # only the used prefix), so it stays zero — re-resizable
        return dense, lat - lr * gl, loss

    def step_fn(state, batch):
        dense, lat = state
        dense, lat, loss = jstep(dense, lat, batch)
        return (dense, lat), loss

    layout = {"flat_total": rows * row_total, "used": canon,
              "stacked": {"rows": rows, "row_total": row_total,
                          "row_used": row_used}}
    return split(full0), step_fn, layout


def _moe_batch(step):
    rng = np.random.RandomState(500 + step)
    return jnp.asarray(rng.randint(0, 64, (4, 8)).astype("int32"))


def test_chaos_resize_ep_lattice_2_to_3_bitwise(tmp_path):
    """ACCEPTANCE (ISSUE 17): resize@4:3 kills a 2-shard ep-flagship
    run mid-epoch; the 3-shard resume reshards the expert lattice
    through elastic (non-divisible 2 -> 3, real tail padding) and
    finishes BITWISE-identical to a clean 3-shard run started from the
    same checkpoint via an independent numpy import."""
    state2, step2, layout2 = _moe_lattice_harness(2)
    state3, step3, layout3 = _moe_lattice_harness(3)
    assert layout2["stacked"]["row_total"] * 3 != layout3["flat_total"]
    d = tmp_path / "ep"

    plan = faults.parse("resize@4:3")
    _, r1 = TrainGuard(step2, _gcfg(d, 2, layout2), plan=plan).run(
        state2, _moe_batch, 8)
    assert r1.status == "preempted" and r1.final_step == 4
    assert r1.resize_to == 3 and r1.faults_injected == 1

    # the independent comparator: numpy re-slice of the lattice leaf
    # (no elastic code), then the remaining steps plain 3-shard
    ck_step, payload, meta = CheckpointManager(str(d)).load_latest(
        with_meta=True)
    assert ck_step == 4 and meta["world_size"] == 2
    _, treedef2 = jax.tree_util.tree_flatten(state2)
    dense_s, lat_s = jax.tree_util.tree_unflatten(treedef2,
                                                  payload["leaves"])
    blk = meta["layout"]["stacked"]
    flat = np.concatenate([np.asarray(lat_s)[i, :u]
                           for i, u in enumerate(blk["row_used"]) if u])
    lat3_ref, _ = _pack_lattice(flat, 3)
    state_b = (jax.tree_util.tree_map(jnp.asarray, dense_s),
               jnp.asarray(lat3_ref))
    for i in range(ck_step, 8):
        state_b, _ = step3(state_b, _moe_batch(i))

    er = elastic.ElasticResume()
    state_a, r2 = TrainGuard(step3, _gcfg(d, 3, layout3), plan=plan,
                             elastic=er).run(state3, _moe_batch, 8)
    assert r2.status == "completed" and r2.final_step == 8
    assert r2.resumed_from == 4 and r2.resharded_from == 2

    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(state_a),
            jax.tree_util.tree_leaves_with_path(state_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(kp))
    # the resized lattice kept its padding exactly zero
    lat_a = np.asarray(state_a[1])
    assert lat_a.shape == (3, layout3["stacked"]["row_total"])
    assert not np.any(lat_a.reshape(-1)[layout3["used"]:])


def test_old_manifest_degrades_with_typed_warning(harnesses, tmp_path):
    """A manifest written by an older PR (no meta): same-world resume
    still works, with a ManifestCompatWarning — and never a KeyError."""
    state4, step4, layout4 = harnesses[4]
    d = tmp_path / "old"
    _, r1 = TrainGuard(step4, _gcfg(d, 4, layout4),
                       plan=faults.parse("preempt@4")).run(
        state4, _make_batch, 8)
    assert r1.status == "preempted"
    # strip the meta, as an old-version manifest would look
    mpath = d / "MANIFEST.json"
    doc = json.loads(mpath.read_text())
    doc.pop("meta", None)
    mpath.write_text(json.dumps(doc))

    er = elastic.ElasticResume()
    with pytest.warns(ManifestCompatWarning, match="same-world"):
        _, r2 = TrainGuard(step4, _gcfg(d, 4, layout4), elastic=er).run(
            state4, _make_batch, 8)
    assert r2.status == "completed" and r2.resumed_from == 4
    assert r2.resharded_from is None


# ---------------------------------------------------------------------------
# plan.from_tuning chips mismatch -> re-plan trigger (satellite: plan.py)
# ---------------------------------------------------------------------------

def test_from_tuning_mismatch_replans_when_installed(tmp_path, monkeypatch):
    from apex_tpu.utils import tuning
    prof_file = tmp_path / "tuned_defaults.json"
    prof_file.write_text(json.dumps({"plan_dp": 8}))
    monkeypatch.setenv("APEX_TPU_TUNING_FILE", str(prof_file))
    tuning.reload()
    try:
        # legacy behavior without the hook: mismatch -> None
        assert plan_mod.from_tuning(4, tpu_only=False) is None
        # installed: mismatch -> a fresh search at the live chip count
        reg = Registry(sink=MemorySink(), flush_interval=0,
                       rank0_only=False)
        events.set_default(reg)
        er = elastic.install(profile=_tiny_profile())
        assert elastic.installed() is er
        replanned = plan_mod.from_tuning(4, tpu_only=False)
        assert replanned is not None and replanned.chips == 4
        # matching chips never consults the hook
        assert plan_mod.from_tuning(8, tpu_only=False).dp == 8
        evs = [r for r in reg.flush() if r.get("name") == "elastic.replan"]
        assert len(evs) == 1
        assert evs[0]["fields"]["old_knobs"]["dp"] == 8
        elastic.uninstall()
        assert elastic.installed() is None
        assert plan_mod.from_tuning(4, tpu_only=False) is None
    finally:
        monkeypatch.delenv("APEX_TPU_TUNING_FILE")
        tuning.reload()


def test_reshard_payload_rejects_model_change():
    """A leaf-count or incompatible-shape difference is a model change,
    not a world change — typed error with detail, never a mis-slice."""
    meta = {"world_size": 8,
            "layout": {"flat_total": 1024, "used": 512, "chunk": 1024,
                       "lane": 128}}
    tmpl = {"a": jnp.zeros((512,), jnp.float32)}
    payload = {"step": 1, "leaves": [np.zeros((1024,), np.float32),
                                     np.zeros((4,), np.float32)]}
    with pytest.raises(WorldSizeMismatchError, match="leaves"):
        elastic.reshard_payload(tmpl, payload, meta, 4)
    payload = {"step": 1, "leaves": [np.zeros((768,), np.float32)]}
    with pytest.raises(WorldSizeMismatchError, match="cannot be resharded"):
        elastic.reshard_payload(tmpl, payload, meta, 4)
    # missing layout -> typed error, not KeyError
    with pytest.raises(WorldSizeMismatchError, match="layout"):
        elastic.reshard_payload(tmpl, {"step": 1, "leaves": []},
                                {"world_size": 8}, 4)
