"""Fused-optimizer oracle tests vs torch.optim (CPU) — the direct analog of
tests/L0/run_optimizers/test_adam.py:8-60 (tolerance max_abs_diff <= 1e-3
over 7 iters) and test_lamb.py's hand-written RefLAMB oracle."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torch

from apex_tpu.optimizers import (FusedAdam, FusedSGD, FusedLAMB,
                                 FusedNovoGrad, FusedAdagrad)

SHAPES = [(31, 13), (128,), (5, 7, 11)]
ITERS = 7
TOL = 1e-3   # matches reference max_abs_diff tolerance


def make_params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), len(SHAPES))
    return {f"p{i}": jax.random.normal(k, s) * 0.5
            for i, (k, s) in enumerate(zip(ks, SHAPES))}


def make_grads(seed):
    ks = jax.random.split(jax.random.PRNGKey(seed + 100), len(SHAPES))
    return {f"p{i}": jax.random.normal(k, s)
            for i, (k, s) in enumerate(zip(ks, SHAPES))}


def to_torch(tree):
    return [torch.tensor(np.asarray(tree[f"p{i}"]), requires_grad=True)
            for i in range(len(SHAPES))]


def run_jax(opt, params, iters=ITERS):
    state = opt.init(params)
    step = jax.jit(lambda s, g, p: opt.step(s, g, p))
    for i in range(iters):
        params, state = step(state, make_grads(i), params)
    return params


def run_torch(topt, tparams, iters=ITERS):
    for i in range(iters):
        grads = make_grads(i)
        for j, p in enumerate(tparams):
            p.grad = torch.tensor(np.asarray(grads[f"p{j}"]))
        topt.step()
    return tparams


def assert_close(params, tparams):
    for i, tp in enumerate(tparams):
        diff = np.abs(np.asarray(params[f"p{i}"]) - tp.detach().numpy())
        assert diff.max() <= TOL, f"p{i}: max diff {diff.max()}"


@pytest.mark.parametrize("impl", ["xla", "fused"])
@pytest.mark.parametrize("adamw,wd", [(True, 0.0), (True, 0.01), (False, 0.0),
                                      (False, 0.01)])
def test_adam_vs_torch(impl, adamw, wd):
    params = make_params()
    opt = FusedAdam(lr=1e-2, weight_decay=wd, adam_w_mode=adamw, impl=impl)
    tparams = to_torch(params)
    if adamw:
        topt = torch.optim.AdamW(tparams, lr=1e-2, weight_decay=wd, eps=1e-8)
    else:
        topt = torch.optim.Adam(tparams, lr=1e-2, weight_decay=wd, eps=1e-8)
    assert_close(run_jax(opt, params), run_torch(topt, tparams))


@pytest.mark.parametrize("impl", ["xla", "fused"])
@pytest.mark.parametrize("momentum,nesterov,wd", [
    (0.0, False, 0.0), (0.9, False, 0.0), (0.9, True, 0.0), (0.9, False, 1e-4)])
def test_sgd_vs_torch(impl, momentum, nesterov, wd):
    params = make_params()
    opt = FusedSGD(lr=0.1, momentum=momentum, nesterov=nesterov,
                   weight_decay=wd, impl=impl)
    tparams = to_torch(params)
    topt = torch.optim.SGD(tparams, lr=0.1, momentum=momentum,
                           nesterov=nesterov, weight_decay=wd)
    assert_close(run_jax(opt, params), run_torch(topt, tparams))


def test_adagrad_vs_torch():
    params = make_params()
    opt = FusedAdagrad(lr=0.1, eps=1e-10)
    tparams = to_torch(params)
    topt = torch.optim.Adagrad(tparams, lr=0.1, eps=1e-10)
    assert_close(run_jax(opt, params), run_torch(topt, tparams))


class RefLAMB:
    """Hand-written LAMB oracle, ported from the reference's test
    (tests/L0/run_optimizers/test_lamb.py:10-60) in numpy."""

    def __init__(self, params, lr=1e-3, betas=(0.9, 0.999), eps=1e-6, wd=0.01,
                 max_grad_norm=1.0):
        self.params = {k: np.asarray(v, np.float64) for k, v in params.items()}
        self.m = {k: np.zeros_like(v) for k, v in self.params.items()}
        self.v = {k: np.zeros_like(v) for k, v in self.params.items()}
        self.t = 0
        self.lr, (self.b1, self.b2) = lr, betas
        self.eps, self.wd, self.max_gn = eps, wd, max_grad_norm

    def step(self, grads):
        self.t += 1
        gnorm = np.sqrt(sum(np.sum(np.asarray(g, np.float64) ** 2)
                            for g in grads.values()))
        clip = 1.0 / max(1.0, gnorm / self.max_gn)
        rc1 = 1.0 / (1.0 - self.b1 ** self.t)
        rc2 = 1.0 / (1.0 - self.b2 ** self.t)
        for k, p in self.params.items():
            g = np.asarray(grads[k], np.float64) * clip
            self.m[k] = self.b1 * self.m[k] + (1 - self.b1) * g
            self.v[k] = self.b2 * self.v[k] + (1 - self.b2) * g * g
            u = (self.m[k] * rc1) / (np.sqrt(self.v[k] * rc2) + self.eps) \
                + self.wd * p
            wn = np.sqrt(np.sum(p * p))
            un = np.sqrt(np.sum(u * u))
            ratio = wn / un if (wn > 0 and un > 0) else 1.0
            self.params[k] = p - self.lr * ratio * u


@pytest.mark.parametrize("impl", ["xla", "fused"])
def test_lamb_vs_ref(impl):
    params = make_params()
    opt = FusedLAMB(lr=1e-2, weight_decay=0.01, impl=impl)
    ref = RefLAMB(params, lr=1e-2, wd=0.01)
    state = opt.init(params)
    step = jax.jit(lambda s, g, p: opt.step(s, g, p))
    p = params
    for i in range(ITERS):
        g = make_grads(i)
        p, state = step(state, g, p)
        ref.step(g)
    for k in p:
        diff = np.abs(np.asarray(p[k]) - ref.params[k])
        assert diff.max() <= TOL, f"{k}: {diff.max()}"


@pytest.mark.parametrize("grad_averaging", [True, False])
@pytest.mark.parametrize("adamw", [True, False])
def test_lamb_fused_matches_xla_knobs(grad_averaging, adamw):
    """Fused vs XLA parity across constructor knobs — regression for the
    round-1 bug where the fused stage-1 kernel hard-coded (1-beta1) and
    silently ignored grad_averaging=False (multi_tensor_lamb.cu:41 passes
    beta3 explicitly)."""
    params = make_params()
    kw = dict(lr=1e-2, weight_decay=0.01, grad_averaging=grad_averaging,
              adam_w_mode=adamw)
    px = run_jax(FusedLAMB(impl="xla", **kw), params)
    pf = run_jax(FusedLAMB(impl="fused", **kw), params)
    for k in px:
        np.testing.assert_allclose(np.asarray(px[k]), np.asarray(pf[k]),
                                   atol=1e-5, err_msg=k)


@pytest.mark.parametrize("norm_type", [2, 0])
@pytest.mark.parametrize("reg_inside,grad_averaging,init_zero", [
    (False, True, False), (True, False, True), (False, False, False)])
def test_novograd_fused_matches_xla_knobs(norm_type, reg_inside,
                                          grad_averaging, init_zero):
    """impl='fused' (flat buffer + segment per-layer norms) must match the
    per-leaf XLA path over every knob combination — regression for round-1's
    silent impl='xla' fallback (fused_novograd.py:33)."""
    params = make_params()
    kw = dict(lr=1e-2, weight_decay=0.01, norm_type=norm_type,
              reg_inside_moment=reg_inside, grad_averaging=grad_averaging,
              init_zero=init_zero)
    px = run_jax(FusedNovoGrad(impl="xla", **kw), params)
    pf = run_jax(FusedNovoGrad(impl="fused", **kw), params)
    for k in px:
        np.testing.assert_allclose(np.asarray(px[k]), np.asarray(pf[k]),
                                   atol=1e-5, err_msg=k)


def test_novograd_runs_and_descends():
    """NovoGrad has no torch oracle; check loss descent + state shapes
    (reference checks numerics vs its own CUDA kernel; our oracle is the
    formula itself)."""
    params = make_params()
    opt = FusedNovoGrad(lr=1e-2, weight_decay=0.01)
    state = opt.init(params)
    # v must be scalar per tensor
    assert all(v.shape == () for v in jax.tree_util.tree_leaves(state.v))
    p = params
    for i in range(3):
        g = make_grads(i)
        p, state = opt.step(state, g, p)
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree_util.tree_leaves(p))
    assert int(state.count) == 3


@pytest.mark.parametrize("impl", ["xla", "fused"])
def test_adam_scale_interop(impl):
    """grads pre-multiplied by scale, step(scale=s) must match unscaled run."""
    params = make_params()
    opt = FusedAdam(lr=1e-2, impl=impl)
    s1, s2 = opt.init(params), opt.init(params)
    g = make_grads(0)
    g_scaled = jax.tree_util.tree_map(lambda x: x * 128.0, g)
    p1, _ = opt.step(s1, g, params)
    p2, _ = opt.step(s2, g_scaled, params, scale=128.0)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   atol=1e-6)


# ---------------------------------------------------------------------------
# state_dtype: narrow (bf16) moment storage on the flat engine (r5 HBM push)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cls", [FusedAdam, FusedLAMB])
def test_state_dtype_bf16_moments_track_fp32(cls):
    """bf16-stored moments (fp32 math, narrow storage) must stay close to
    the fp32-state trajectory — the documented trade-off is precision of
    the STORED moments only, not of the arithmetic."""
    params = make_params()
    opt32 = cls(lr=1e-2, impl="fused")
    opt16 = cls(lr=1e-2, impl="fused", state_dtype=jnp.bfloat16)
    s32, s16 = opt32.init(params), opt16.init(params)
    assert s16.m.dtype == jnp.bfloat16 and s16.v.dtype == jnp.bfloat16
    assert s16.master.dtype == jnp.float32      # master never narrows
    fl = opt32.flattener
    for i in range(ITERS):
        g = fl.flatten(make_grads(i))
        s32 = opt32.step_flat(s32, g)
        s16 = opt16.step_flat(s16, g)
    assert s16.m.dtype == jnp.bfloat16 and s16.v.dtype == jnp.bfloat16
    p32, p16 = np.asarray(s32.master), np.asarray(s16.master)
    # loose: bf16 moment rounding (~2-3 decimal digits in v) feeds back
    # into the update direction; a few % drift after 7 random-grad steps
    # is the documented trade-off, an order-of-magnitude divergence or a
    # NaN is a bug
    assert np.isfinite(p16).all()
    denom = np.maximum(np.abs(p32), 1e-3)
    rel = np.abs(p32 - p16) / denom
    assert rel.max() < 6e-2, f"max rel drift {rel.max()}"


def test_state_dtype_requires_fused_impl():
    with pytest.raises(ValueError, match="flat-engine"):
        FusedAdam(lr=1e-2, impl="xla", state_dtype=jnp.bfloat16)


def test_state_dtype_rejects_non_float():
    with pytest.raises(ValueError, match="float dtype"):
        FusedAdam(lr=1e-2, impl="fused", state_dtype=jnp.int8)
