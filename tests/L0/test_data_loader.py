"""Native prefetch engine tests (csrc/prefetch.cpp via apex_tpu.data).

Oracle pattern: gather correctness is checked structurally (row content
encodes the sample index, so every batch proves its own gather) rather than
by predicting the shuffle; epochs must be exact permutations; the native
path must be deterministic for any worker count (strict ticket ordering).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu.data import (ArraySource, NativeLoader, SyntheticSource,
                           native_available)


def _collect(loader):
    return [(np.asarray(x), np.asarray(y)) for x, y in loader]


def _indexed_source(n=64, d=8):
    # row i filled with value i, label i: any gathered row self-identifies
    data = np.repeat(np.arange(n, dtype=np.float32)[:, None], d, axis=1)
    labels = np.arange(n, dtype=np.int32)
    return ArraySource(data=data, labels=labels)


@pytest.mark.parametrize("threads", [1, 3])
def test_gather_epoch_is_permutation(threads):
    n, d, b = 64, 8, 16
    src = _indexed_source(n, d)
    batches = _collect(NativeLoader(src, batch_size=b, steps=n // b,
                                    threads=threads, seed=7))
    seen = []
    for x, y in batches:
        assert x.shape == (b, d) and x.dtype == np.float32
        assert y.shape == (b,) and y.dtype == np.int32
        # gather correctness: every row's content equals its label
        np.testing.assert_array_equal(x[:, 0].astype(np.int32), y)
        np.testing.assert_array_equal(x, x[:, :1].repeat(d, axis=1))
        seen.extend(y.tolist())
    # one epoch = exactly one visit per sample
    assert sorted(seen) == list(range(n))


def test_second_epoch_reshuffles():
    n, b = 64, 16
    src = _indexed_source(n)
    two_epochs = _collect(NativeLoader(src, batch_size=b,
                                       steps=2 * (n // b), seed=3))
    e1 = np.concatenate([y for _, y in two_epochs[: n // b]])
    e2 = np.concatenate([y for _, y in two_epochs[n // b:]])
    assert sorted(e1.tolist()) == sorted(e2.tolist()) == list(range(n))
    assert not np.array_equal(e1, e2), "epoch order did not reshuffle"


def test_deterministic_across_worker_counts():
    if not native_available():
        pytest.skip("no native toolchain")
    src = _indexed_source(48, 4)
    a = _collect(NativeLoader(src, batch_size=12, steps=8, threads=1, seed=5))
    b = _collect(NativeLoader(src, batch_size=12, steps=8, threads=4, seed=5))
    for (xa, ya), (xb, yb) in zip(a, b):
        np.testing.assert_array_equal(ya, yb)
        np.testing.assert_array_equal(xa, xb)


def test_synthetic_batches():
    src = SyntheticSource(shape=(4, 4, 3), n_classes=10)
    batches = _collect(NativeLoader(src, batch_size=8, steps=3, seed=1))
    assert len(batches) == 3
    for x, y in batches:
        assert x.shape == (8, 4, 4, 3) and x.dtype == np.float32
        assert np.all((x >= -1.0) & (x < 1.0))
        assert np.all((y >= 0) & (y < 10))
    assert not np.array_equal(batches[0][0], batches[1][0])


def test_device_put_yields_jax_arrays():
    src = SyntheticSource(shape=(2,), n_classes=2)
    for x, y in NativeLoader(src, batch_size=4, steps=1):
        assert isinstance(x, jnp.ndarray) and isinstance(y, jnp.ndarray)


def test_python_fallback_same_contract(monkeypatch):
    from apex_tpu.data import loader as L
    monkeypatch.setattr(L, "_load", lambda: None)
    n, b = 32, 8
    src = _indexed_source(n)
    seen = []
    for x, y in NativeLoader(src, batch_size=b, steps=n // b, seed=2):
        np.testing.assert_array_equal(
            np.asarray(x)[:, 0].astype(np.int32), np.asarray(y))
        seen.extend(np.asarray(y).tolist())
    assert sorted(seen) == list(range(n))


def test_python_fallback_producer_error_surfaces(monkeypatch):
    """A producer-thread crash must raise in the consumer, not hang it
    (the advisor's finding: no sentinel on unexpected death left q.get()
    blocked forever)."""
    import pytest
    from apex_tpu.data import loader as L
    monkeypatch.setattr(L, "_load", lambda: None)
    src = _indexed_source(16)

    real_shape = src.data.shape

    class Bomb:
        shape = real_shape

        def __getitem__(self, idx):
            raise RuntimeError("bad memmap index")

    src.data = Bomb()
    it = iter(NativeLoader(src, batch_size=4, steps=4, seed=0))
    with pytest.raises(RuntimeError, match="bad memmap index"):
        next(it)


def test_native_engine_compiles():
    """The toolchain is baked into this image; the native path must be
    genuinely exercised in CI, not silently skipped via the fallback."""
    assert native_available()


def test_ring_soak_random_configs():
    """Concurrency soak of the C++ ring: random (depth, threads, batch)
    combos, interleaved early-abandoned iterators (destroys a live ring),
    every batch still gather-correct and epochs exact."""
    if not native_available():
        pytest.skip("no native toolchain")
    rng = np.random.RandomState(0)
    n, d = 96, 4
    src = _indexed_source(n, d)
    for trial in range(8):
        depth = int(rng.randint(2, 6))
        threads = int(rng.randint(1, 6))
        batch = int(rng.choice([8, 12, 24, 48]))
        steps = (n // batch) * 2
        it = iter(NativeLoader(src, batch_size=batch, steps=steps,
                               depth=depth, threads=threads, seed=trial,
                               device_put=False))
        seen = []
        for i, (x, y) in enumerate(it):
            np.testing.assert_array_equal(x[:, 0].astype(np.int32), y)
            seen.extend(y.tolist())
            if trial % 3 == 2 and i == 1:
                break                  # abandon mid-epoch: ring must clean up
        if trial % 3 != 2:
            assert sorted(seen[:n]) == list(range(n)), "epoch not exact"
