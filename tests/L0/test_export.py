"""``apex_tpu.telemetry.export`` (ISSUE 20): live OpenMetrics export
of the registry's flush window.

The contract under test:

  * the exposition format is pinned (types, counter ``_total``,
    histogram stat series, name sanitization, ``# EOF`` terminator);
  * a live scrape mid-run returns THE SAME values the JSONL stream
    recorded for that flush window — the exporter is a copy of the
    flush, not a second measurement;
  * zero new host syncs: the ``jax.device_get`` count per flush is
    identical with the exporter on and off (the snapshot rides the
    flush's existing batched window);
  * disabled mode is a true no-op — no exporter object, no thread, no
    env read beyond ``maybe_start``;
  * ``APEX_TPU_METRICS_PORT`` gating + ``maybe_start`` idempotency;
  * TrainGuard arms the process default around a run and records the
    URL in its report, then tears it down.
"""
import json
import threading
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.resilience import GuardConfig, TrainGuard
from apex_tpu.telemetry import JsonlSink, Registry, export
from apex_tpu.telemetry import events as events_mod
from apex_tpu.telemetry import trace as trace_mod
from apex_tpu.telemetry.export import MetricsExporter, render_openmetrics


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    monkeypatch.delenv(export.ENV_PORT, raising=False)
    prev_exp = export.install(None)
    prev_reg = events_mod.set_default(None)
    prev_tr = trace_mod.set_tracer(None)
    yield
    export.shutdown()            # close anything a test armed
    export.install(prev_exp)
    events_mod.set_default(prev_reg)
    trace_mod.set_tracer(prev_tr)


def _samples(text):
    """name (incl. any label suffix) -> value string, sample lines only."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, val = line.rpartition(" ")
        out[name] = val
    return out


# ---------------------------------------------------------------------------
# the exposition format (pure function)
# ---------------------------------------------------------------------------

def test_render_openmetrics_format():
    snap = {
        "loss": {"type": "gauge", "value": 1.5},
        "examples": {"type": "counter", "value": 32},
        "serve.queue_depth": {"type": "gauge", "value": 3},
        "step_time_ms": {"type": "histogram",
                         "stats": {"count": 2, "sum": 10.0, "min": 4.0,
                                   "max": 6.0, "mean": 5.0}},
    }
    text = render_openmetrics(snap, {"run": "r1", "step": 8,
                                     "flushes": 4}, {"resumed": 2})
    assert text.endswith("# EOF\n")
    s = _samples(text)
    assert s['apex_tpu_build_info{run="r1"}'] == "1"
    assert s["apex_tpu_last_flush_step"] == "8"
    assert s["apex_tpu_flushes"] == "4"
    assert s["apex_tpu_loss"] == "1.5"
    # counters get the _total suffix and the counter type line
    assert s["apex_tpu_examples_total"] == "32"
    assert "# TYPE apex_tpu_examples_total counter" in text
    assert "# TYPE apex_tpu_loss gauge" in text
    # dots sanitize to underscores
    assert s["apex_tpu_serve_queue_depth"] == "3"
    # histograms expand to the five stat series
    for stat, v in (("count", "2"), ("sum", "10"), ("min", "4"),
                    ("max", "6"), ("mean", "5")):
        assert s[f"apex_tpu_step_time_ms_{stat}"] == v
    assert s['apex_tpu_events_total{name="resumed"}'] == "2"


def test_env_port_parsing(monkeypatch):
    assert export.env_port() is None                  # unset
    for bad in ("", "  ", "nope", "-1", "70000", "8.5"):
        monkeypatch.setenv(export.ENV_PORT, bad)
        assert export.env_port() is None, bad
    monkeypatch.setenv(export.ENV_PORT, "0")          # ephemeral is real
    assert export.env_port() == 0
    monkeypatch.setenv(export.ENV_PORT, " 9101 ")
    assert export.env_port() == 9101


# ---------------------------------------------------------------------------
# live scrape == the JSONL flush window
# ---------------------------------------------------------------------------

def test_live_scrape_matches_jsonl_flush_window(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    with MetricsExporter(port=0, run_id="scrape-run") as exp:
        reg = Registry(sink=JsonlSink(str(path)), flush_interval=2,
                       rank0_only=False, run_id="scrape-run",
                       exporter=exp)
        for i in range(4):
            with reg.step():
                reg.gauge("loss").set(2.0 - 0.25 * i)
                reg.counter("examples").add(8)
            if i == 1:
                reg.event("resumed", step=2)   # drains at the next flush
        with urllib.request.urlopen(exp.url, timeout=5) as resp:
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            body = resp.read().decode()
        reg.close()
    s = _samples(body)
    # the scrape IS the last flush window the JSONL recorded
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    last_loss = [r for r in recs if r.get("name") == "loss"][-1]
    assert float(s["apex_tpu_loss"]) == last_loss["value"]
    last_hist = [r for r in recs if r.get("name") == "step_time_ms"
                 and (r.get("stats") or {}).get("count")][-1]["stats"]
    assert float(s["apex_tpu_step_time_ms_count"]) == last_hist["count"]
    assert float(s["apex_tpu_step_time_ms_mean"]) == pytest.approx(
        last_hist["mean"])
    assert s['apex_tpu_build_info{run="scrape-run"}'] == "1"
    assert s["apex_tpu_last_flush_step"] == "4"
    assert s['apex_tpu_events_total{name="resumed"}'] == "1"
    # the /json view carries the same snapshot
    with MetricsExporter(port=0) as e2:
        e2.observe_flush(None, [{"kind": "metric", "ts": "t", "step": 1,
                                 "name": "x", "type": "gauge",
                                 "value": 7.0}])
        with urllib.request.urlopen(
                e2.url.replace("/metrics", "/json"), timeout=5) as resp:
            doc = json.loads(resp.read().decode())
        assert doc["metrics"]["x"]["value"] == 7.0
        # unknown paths 404 instead of leaking
        bad = e2.url.replace("/metrics", "/nope")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(bad, timeout=5)


# ---------------------------------------------------------------------------
# zero new host syncs
# ---------------------------------------------------------------------------

def _drive(reg):
    for i in range(4):
        with reg.step():
            # device values: the flush's batched window must resolve
            reg.gauge("loss").set(jnp.float32(i))
            reg.counter("examples").add(4)
    reg.close()


def test_exporter_adds_zero_device_gets(monkeypatch):
    """The flush's batched window already pays its one ``device_get``;
    the exporter must not add another."""
    counts = []
    real_get = jax.device_get

    def run(exporter):
        calls = [0]
        monkeypatch.setattr(
            jax, "device_get",
            lambda x: (calls.__setitem__(0, calls[0] + 1),
                       real_get(x))[1])
        reg = Registry(flush_interval=2, rank0_only=False,
                       exporter=exporter)
        _drive(reg)
        monkeypatch.setattr(jax, "device_get", real_get)
        counts.append(calls[0])

    exp = MetricsExporter(port=0)          # unstarted: pure snapshot
    run(exp)
    run(False)                             # hard opt-out
    assert counts[0] == counts[1]
    assert counts[0] > 0                   # the harness saw real flushes
    # and the snapshot actually landed while costing nothing extra
    assert exp._snapshot["loss"]["value"] == 3.0
    assert exp._meta["flushes"] >= 2


def test_disabled_mode_is_a_true_noop(monkeypatch):
    monkeypatch.delenv(export.ENV_PORT, raising=False)
    before = {t.name for t in threading.enumerate()}
    assert export.maybe_start(run_id="r") is None
    assert export.get_exporter() is None
    reg = Registry(flush_interval=2, rank0_only=False)
    _drive(reg)
    assert export.get_exporter() is None
    after = {t.name for t in threading.enumerate()}
    assert "apex-tpu-metrics" not in after - before


def test_registry_exporter_false_opts_out_of_the_default():
    """``exporter=False`` bypasses even an installed process default —
    a registry can opt out of a fleet-armed endpoint."""
    exp = MetricsExporter(port=0)
    export.install(exp)
    reg = Registry(flush_interval=2, rank0_only=False, exporter=False)
    _drive(reg)
    assert exp._snapshot == {}
    # and the default DOES receive flushes from a registry that didn't
    reg2 = Registry(flush_interval=2, rank0_only=False)
    _drive(reg2)
    assert exp._snapshot["loss"]["value"] == 3.0
    export.install(None)


def test_maybe_start_idempotent_and_shutdown(monkeypatch):
    monkeypatch.setenv(export.ENV_PORT, "0")
    e1 = export.maybe_start(run_id="first")
    assert e1 is not None and e1.port is not None
    assert e1.url == f"http://127.0.0.1:{e1.port}/metrics"
    e2 = export.maybe_start(run_id="second")
    assert e2 is e1                        # one endpoint per process
    assert e1._meta["run"] == "second"     # identity refreshed
    export.shutdown()
    assert export.get_exporter() is None
    assert e1.port is None                 # socket released


# ---------------------------------------------------------------------------
# TrainGuard integration: armed around the run, URL in the report
# ---------------------------------------------------------------------------

def _sgd_step():
    @jax.jit
    def step(w, batch):
        g = jax.grad(lambda w: jnp.sum((w - batch) ** 2))(w)
        return w - 0.1 * g, jnp.sum((w - batch) ** 2)
    return step


def test_guard_arms_export_and_reports_url(tmp_path, monkeypatch):
    monkeypatch.setenv(export.ENV_PORT, "0")
    urls = []

    def batches(i):
        exp = export.get_exporter()
        if exp is not None and exp.url:
            urls.append(exp.url)           # live DURING the run
        return jnp.asarray(np.random.RandomState(i).randn(4).astype(
            np.float32))

    cfg = GuardConfig(ckpt_dir=str(tmp_path / "ck"), save_every_steps=4,
                      check_every=2, backoff_seconds=0.01, enabled=True)
    _, rep = TrainGuard(_sgd_step(), cfg).run(jnp.zeros(4), batches, 6)
    assert rep.status == "completed"
    assert rep.export_url is not None
    assert rep.export_url.startswith("http://127.0.0.1:")
    assert urls and urls[0] == rep.export_url
    # guard owns what it armed: torn down after the run
    assert export.get_exporter() is None


def test_guard_without_env_reports_no_url(tmp_path, monkeypatch):
    monkeypatch.delenv(export.ENV_PORT, raising=False)
    cfg = GuardConfig(ckpt_dir=str(tmp_path / "ck"), save_every_steps=4,
                      check_every=2, backoff_seconds=0.01, enabled=True)
    _, rep = TrainGuard(_sgd_step(), cfg).run(
        jnp.zeros(4),
        lambda i: jnp.asarray(
            np.random.RandomState(i).randn(4).astype(np.float32)), 4)
    assert rep.status == "completed"
    assert rep.export_url is None
    assert export.get_exporter() is None
