"""Worker for the 2-process sharded-checkpoint e2e test: save_sharded /
load_sharded over the GLOBAL mesh spanning both processes.  Exercises the
multi-host protocol the advisor flagged: the collective orbax write must
target ONE deterministic temp dir (all processes agree), and the
swap/cleanup of the shared path must run on process 0 only, fenced by
barriers.  Saves twice so the overwrite (rename/rmtree swap) path runs
under a real process boundary, then restores and digests."""
import faulthandler
import os
import signal

faulthandler.register(signal.SIGUSR1)

from apex_tpu.utils.platform import force_cpu

force_cpu(2)

import numpy as np

from apex_tpu.parallel import initialize_distributed

initialize_distributed()

import jax                        # noqa: E402
import jax.numpy as jnp           # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from apex_tpu import checkpoint   # noqa: E402

rank = jax.process_index()
assert jax.process_count() == 2
path = os.environ["APEX_TPU_TEST_CKPT"]

mesh = Mesh(np.array(jax.devices()), ("data",))
sh = NamedSharding(mesh, P("data"))
rep = NamedSharding(mesh, P())


def make_tree(scale):
    return {
        "w": jax.device_put(
            scale * jnp.arange(64, dtype=jnp.float32).reshape(16, 4), sh),
        "step": jax.device_put(jnp.int32(7), rep),
        "m": {"v": jax.device_put(scale * jnp.ones((16, 4)), sh)},
    }


checkpoint.save_sharded(path, make_tree(1.0))
# overwrite: swap must be lead-only + barrier-fenced, and the new content
# (scale=2) must fully replace the old
tree2 = make_tree(2.0)
checkpoint.save_sharded(path, tree2)

template = jax.tree_util.tree_map(
    lambda x: jax.device_put(jnp.zeros_like(x), x.sharding), tree2)
got = checkpoint.load_sharded(path, template)
# a global array spanning both hosts can't be device_get in one piece —
# compare the shards this process owns, leaf by leaf
for a, b in zip(jax.tree_util.tree_leaves(tree2),
                jax.tree_util.tree_leaves(got)):
    sa = sorted(a.addressable_shards, key=lambda s: str(s.index))
    sb = sorted(b.addressable_shards, key=lambda s: str(s.index))
    assert len(sa) == len(sb) > 0
    for x, y in zip(sa, sb):
        assert x.index == y.index
        np.testing.assert_array_equal(np.asarray(x.data), np.asarray(y.data))
    assert a.sharding == b.sharding, (a.sharding, b.sharding)

from jax.experimental import multihost_utils  # noqa: E402

w_global = multihost_utils.process_allgather(got["w"], tiled=True)
digest = float(np.abs(np.asarray(w_global)).sum())
leftover = [p for p in (f"{path}.new", f"{path}.old") if os.path.exists(p)]
print(f"CKPTOK rank={rank} digest={digest:.6f} leftover={leftover}",
      flush=True)
