"""Worker for the 2-process ZeRO e2e test: DistributedFusedLAMB
(impl='xla' — interpret-mode Pallas under a multi-process Gloo mesh is
not the target; the fused impl is covered in-process and by the dryrun)
sharded over the GLOBAL mesh spanning both processes.  Each DEVICE holds
1/4 of the optimizer state (each rank drives 2 devices, so holds 1/2);
updated params must be identical everywhere and must match the digest
printed by the peer."""
import faulthandler
import signal

faulthandler.register(signal.SIGUSR1)

from apex_tpu.utils.platform import force_cpu

force_cpu(2)

import numpy as np

from apex_tpu.parallel import initialize_distributed

initialize_distributed()

import functools                  # noqa: E402

import jax                        # noqa: E402
import jax.numpy as jnp           # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

try:
    from jax import shard_map
except ImportError:               # older jax layout
    from jax.experimental.shard_map import shard_map

from apex_tpu.contrib.optimizers import DistributedFusedLAMB  # noqa: E402

rank = jax.process_index()
assert jax.process_count() == 2
n = jax.device_count()
mesh = Mesh(np.array(jax.devices()), ("data",))

params = {"w": 0.1 * jax.random.normal(jax.random.PRNGKey(0), (32, 16)),
          "b": jnp.zeros((16,))}
opt = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0,
                           impl="xla", bf16_allgather=True)
rep = jax.tree_util.tree_map(lambda _: P(), params)
sspec = opt.state_pspecs()


@jax.jit
@functools.partial(shard_map, mesh=mesh, in_specs=(rep,), out_specs=sspec)
def init_fn(p):
    return opt.init(p)


@functools.partial(jax.jit, donate_argnums=0)   # in-place state (HBM reuse
# at the jit boundary — the kernels themselves never alias, PERF_NOTES §2)
@functools.partial(shard_map, mesh=mesh, in_specs=(sspec, rep, rep),
                   out_specs=(rep, sspec))
def step_fn(state, grads, p):
    return opt.step(state, grads, p)


state = init_fn(params)
# ZeRO contract: each device owns 1/n of the flat state (the `p` master
# shard; ShardedLAMBState fields are count/p/m/v/gnorm)
shard = state.p.sharding.shard_shape(state.p.shape)
assert shard[0] * n == state.p.shape[0], (shard, state.p.shape, n)

p = params
for i in range(3):
    grads = jax.tree_util.tree_map(
        lambda x: 0.01 * (i + 1) * jnp.ones_like(x), p)
    p, state = step_fn(state, grads, p)
jax.block_until_ready(p)

w = np.asarray(jax.device_get(p["w"]), np.float32)
assert np.isfinite(w).all()
digest = float(np.abs(w).sum())
print(f"ZEROOK rank={rank} count={int(np.asarray(state.count))} "
      f"digest={digest:.6f}", flush=True)
