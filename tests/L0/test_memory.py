"""apex_tpu.telemetry.memory — peak-HBM attribution, live gauges, OOM
post-mortem (ISSUE 6).

The acceptance gates:

  * the HLO liveness sweep is CPU-deterministic on a tiny jitted train
    step, and its per-class table PARTITIONS the peak exactly;
  * the disabled/unsupported memory layer is a true zero-sync/zero-alloc
    no-op (the registry's bar);
  * ``APEX_TPU_FAULTS="oom@7"`` under TrainGuard yields exactly one
    schema-valid ``flight-oom-*.json`` carrying the attribution table
    and ``bad_step=7``, and the run RE-RAISES (no rollback retry burn);
  * ``python -m apex_tpu.telemetry mem`` renders a per-class peak-HBM
    table whose total matches the liveness sweep on the flagship
    transformer step.
"""
import gc
import glob
import json
import os
import subprocess
import sys
import tracemalloc

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.resilience import GuardConfig, TrainGuard, faults
from apex_tpu.telemetry import (MemorySink, Registry, events, memory,
                                report, trace)

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _no_defaults():
    """Tracers/registries/plans/attributions must not leak."""
    prev_tr = trace.set_tracer(None)
    prev_reg = events.set_default(None)
    prev_plan = faults.install(None)
    prev_attr = memory.set_attribution(None)
    yield
    trace.set_tracer(prev_tr)
    events.set_default(prev_reg)
    faults.install(prev_plan)
    memory.set_attribution(prev_attr)


def _opt_state():
    return {"model_params": {"w": jnp.ones((64, 64))},
            "opt": {"m": jnp.zeros((64, 64)), "v": jnp.zeros((64, 64))}}


def _opt_step(state, x):
    """A tiny jitted train step with a real params/optimizer/batch
    split, so the sweep's arg classification has something to find."""
    g = jax.grad(lambda w: (jnp.tanh(x @ w) @ w).sum())(
        state["model_params"]["w"])
    m = state["opt"]["m"] * 0.9 + g
    new_w = state["model_params"]["w"] - 0.01 * m
    return ({"model_params": {"w": new_w},
             "opt": {"m": m, "v": state["opt"]["v"]}},
            (x @ state["model_params"]["w"]).sum())


# ---------------------------------------------------------------------------
# static attribution
# ---------------------------------------------------------------------------

def test_liveness_sweep_partitions_peak_on_tiny_train_step():
    state = _opt_state()
    x = jnp.ones((8, 64))
    t = memory.memory_table(_opt_step, state, x)
    assert t["peak_bytes"] > 0
    assert 0 <= t["peak_index"] < t["n_instructions"]
    # THE invariant: the per-class table partitions the sweep's peak
    assert sum(t["by_class"].values()) == t["peak_bytes"]
    assert set(t["by_class"]) <= set(memory.MEM_CLASSES)
    # the keypath metadata classified the state: weights and moments
    # land in their own classes, the batch in its
    assert t["by_class"]["params"] == 64 * 64 * 4
    assert t["by_class"]["optimizer"] == 2 * 64 * 64 * 4
    assert t["by_class"]["batch"] == 8 * 64 * 4
    # FLOPs joined from attrib.parse_hlo onto the live rows
    assert any(r["flops"] > 0 for r in t["live_at_peak"])
    # deterministic: the same compile walks to the same answer
    t2 = memory.memory_table(_opt_step, state, x)
    assert t2["peak_bytes"] == t["peak_bytes"]
    assert t2["by_class"] == t["by_class"]
    # compiled memory_analysis rides alongside on the CPU backend
    assert t["stats"] is not None and t["stats"]["argument_bytes"] > 0


_HLO_TEMPLATE = """HloModule jit_step, is_scheduled=true{alias}

ENTRY %main.9 (Arg_0.1: f32[256,256], Arg_1.2: f32[4,4]) -> f32[256,256] {{
  %Arg_0.1 = f32[256,256]{{1,0}} parameter(0), metadata={{op_name="state['model_params']['w']"}}
  %negate.3 = f32[256,256]{{1,0}} negate(f32[256,256]{{1,0}} %Arg_0.1)
  %Arg_1.2 = f32[4,4]{{1,0}} parameter(1), metadata={{op_name="x"}}
  %tanh.4 = f32[4,4]{{1,0}} tanh(f32[4,4]{{1,0}} %Arg_1.2)
  ROOT %exponential.5 = f32[256,256]{{1,0}} exponential(f32[256,256]{{1,0}} %negate.3)
}}
"""


def test_liveness_donated_args_release_buffers():
    """Donated parameters die at last use instead of living to program
    end — the sweep reads the input_output_alias header, or every
    in-place update would double-count its state.  Handcrafted HLO so
    the schedule (and therefore the difference) is deterministic."""
    plain = memory.hlo_liveness(_HLO_TEMPLATE.format(alias=""))
    donated = memory.hlo_liveness(_HLO_TEMPLATE.format(
        alias=", input_output_alias={ {}: (0, {}, may-alias) }"))
    n = 256 * 256 * 4
    # non-donated: the param is caller-owned and stays live under the
    # negate/exp chain -> param + negate + output all overlap at the end
    assert plain["peak_bytes"] >= 3 * n
    # donated: the param dies after %negate.3 consumes it
    assert donated["peak_bytes"] < plain["peak_bytes"]
    assert donated["peak_bytes"] >= 2 * n
    for t in (plain, donated):
        assert sum(t["by_class"].values()) == t["peak_bytes"]


_HLO_TUPLE_LOOP = """HloModule jit_loop, is_scheduled=true

ENTRY %main.9 (Arg_0.1: f32[256,256], Arg_1.2: f32[256,256]) -> f32[4,4] {
  %Arg_0.1 = f32[256,256]{1,0} parameter(0), metadata={op_name="a"}
  %Arg_1.2 = f32[256,256]{1,0} parameter(1), metadata={op_name="b"}
  %negate.3 = f32[256,256]{1,0} negate(f32[256,256]{1,0} %Arg_0.1)
  %negate.4 = f32[256,256]{1,0} negate(f32[256,256]{1,0} %Arg_1.2)
  %tuple.5 = (f32[256,256]{1,0}, f32[256,256]{1,0}) tuple(f32[256,256]{1,0} %negate.3, f32[256,256]{1,0} %negate.4)
  %constant.6 = f32[4,4]{1,0} constant({...})
  %tanh.7 = f32[4,4]{1,0} tanh(f32[4,4]{1,0} %constant.6)
  %custom-call.8 = f32[4,4]{1,0} custom-call(f32[4,4]{1,0} %tanh.7, (f32[256,256]{1,0}, f32[256,256]{1,0}) %tuple.5), custom_call_target="consume"
  ROOT %exponential.9 = f32[4,4]{1,0} exponential(f32[4,4]{1,0} %custom-call.8)
}
"""


def test_liveness_tuple_use_keeps_every_element_alive():
    """A consumer of a mid-graph tuple (a while loop's carry, a
    custom-call) must extend the lifetime of ALL its elements — an
    alias collapsed to element 0 would silently understate the peak
    the planner and the OOM dump consume."""
    t = memory.hlo_liveness(_HLO_TUPLE_LOOP)
    n = 256 * 256 * 4
    by_op = {r["op"]: r for r in t["live_at_peak"]}
    # the tuple consumer sits at index 7: BOTH negates must survive to
    # it (an element-0-only alias would end negate.4 at the tuple)
    assert by_op["negate.3"]["last_use"] == 7
    assert by_op["negate.4"]["last_use"] == 7
    assert t["peak_bytes"] >= 4 * n          # 2 params + 2 negates
    assert sum(t["by_class"].values()) == t["peak_bytes"]


def test_memory_model_contract_and_registration():
    state = _opt_state()
    t = memory.memory_table(_opt_step, state, jnp.ones((8, 64)))
    model = memory.memory_model(table=t)
    for key in ("peak_hbm_bytes", "params_bytes", "optimizer_bytes",
                "activations_bytes", "temps_bytes", "output_bytes",
                "by_class", "top", "peak_op"):
        assert key in model, key
    assert model["peak_hbm_bytes"] == t["peak_bytes"]
    assert model["params_bytes"] == t["by_class"]["params"]
    assert json.loads(json.dumps(model)) == model   # planner-consumable
    # register=True (the default) installs it for the OOM post-mortem
    assert memory.get_attribution() is model
    model2 = memory.memory_model(table=t, register=False)
    assert memory.get_attribution() is model       # unchanged


def test_format_memory_table_renders_classes_and_total():
    t = memory.memory_table(_opt_step, _opt_state(), jnp.ones((8, 64)))
    text = memory.format_memory_table(t, top=4)
    assert "peak-HBM attribution" in text
    for cls in ("params", "optimizer", "temps"):
        assert cls in text
    assert "liveness-sweep peak" in text
    assert "memory_analysis" in text


def test_classify_arg_paths():
    assert memory.classify_arg("state['model_params']['w']") == "params"
    assert memory.classify_arg(r"state[\'opt\'][\'m\']") == "optimizer"
    assert memory.classify_arg("state.master_params['fc']") == "optimizer"
    assert memory.classify_arg("state.scalers[0].loss_scale") == "optimizer"
    assert memory.classify_arg("tokens") == "batch"
    assert memory.classify_arg("x") == "batch"
    assert memory.classify_arg("mystery_arg") == "args"


# ---------------------------------------------------------------------------
# live gauges
# ---------------------------------------------------------------------------

class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats
        self.calls = 0

    def memory_stats(self):
        self.calls += 1
        return self._stats


def test_monitor_disabled_is_zero_sync_zero_alloc():
    dev = _FakeDevice({"bytes_in_use": 1})
    mon = memory.MemoryMonitor(enabled=False, device=dev)
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False,
                   memory=False)

    def burn():
        for _ in range(1000):
            assert mon.poll() is None
            assert mon.observe_flush(reg) is None

    burn()                          # warm allocator/caches first
    gc.collect()
    tracemalloc.start()
    snap1 = tracemalloc.take_snapshot()
    burn()
    gc.collect()
    snap2 = tracemalloc.take_snapshot()
    tracemalloc.stop()
    per_call = [s for s in snap2.compare_to(snap1, "lineno")
                if s.count_diff >= 100
                and s.traceback and "tracemalloc" not in
                s.traceback[0].filename]
    assert per_call == [], [str(s) for s in per_call]
    assert dev.calls == 0           # the allocator was never touched
    assert mon.snapshot() == []


def test_monitor_unsupported_backend_probes_exactly_once():
    dev = _FakeDevice(None)         # a backend with no allocator stats
    mon = memory.MemoryMonitor(enabled=True, device=dev)
    reg = Registry(sink=MemorySink(), flush_interval=0, rank0_only=False,
                   memory=False)
    for _ in range(50):
        assert mon.observe_flush(reg) is None
    assert dev.calls == 1           # one probe, then cached unsupported
    assert mon.supported is False


def test_registry_flush_emits_mem_gauges_and_counter_track(tmp_path):
    dev = _FakeDevice({"bytes_in_use": 1000, "peak_bytes_in_use": 2000,
                       "largest_alloc_size": 500, "bytes_limit": 4000})
    tr = trace.Tracer()
    trace.set_tracer(tr)
    sink = MemorySink()
    reg = Registry(sink=sink, flush_interval=0, rank0_only=False,
                   memory=memory.MemoryMonitor(enabled=True, device=dev))
    with reg.step():
        reg.gauge("loss").set(1.0)
    reg.flush()
    names = {r["name"]: r["value"] for r in sink.records
             if r.get("type") == "gauge"}
    assert names["mem.bytes_in_use"] == 1000.0
    assert names["mem.peak_bytes_in_use"] == 2000.0
    assert names["mem.largest_alloc_bytes"] == 500.0
    # records stay schema-valid (the sink validated on write) and the
    # summary's memory line reads them back
    s = report.summarize(sink.records)
    assert s["mem_peak_bytes"] == 2000.0
    assert s["mem_in_use_bytes"] == 1000.0
    assert "memory" in report.format_summary(s)
    # the counter track landed in the chrome export (ph "C") AND the
    # flight ring (the OOM dump shows the curve), schema-valid
    counters = [e for e in tr.export()["traceEvents"]
                if e.get("ph") == "C"]
    assert counters and counters[0]["name"] == "device_mem"
    assert counters[0]["args"]["bytes_in_use"] == 1000.0
    ring = [e for e in tr.recorder.snapshot() if e["kind"] == "counter"]
    assert ring and ring[0]["values"]["peak_bytes_in_use"] == 2000.0
    path = tr.recorder.dump("check", directory=str(tmp_path))
    assert trace.dump_violations(json.load(open(path))) == []
    # the monitor's history feeds the post-mortem
    mon = reg._memory
    assert mon.snapshot()[-1]["bytes_in_use"] == 1000.0


def test_registry_disabled_never_builds_a_monitor(monkeypatch):
    reg = Registry(sink=MemorySink(), enabled=False)
    assert reg._memory is None
    monkeypatch.setenv("APEX_TPU_TELEMETRY_MEM", "0")
    reg2 = Registry(sink=MemorySink(), rank0_only=False)
    assert reg2._memory is None     # env-disabled default monitor


# ---------------------------------------------------------------------------
# OOM post-mortem
# ---------------------------------------------------------------------------

def test_parse_allocator_report_real_shape():
    text = (
        "RESOURCE_EXHAUSTED: XLA:TPU compile permanent error. Ran out of "
        "memory in memory space hbm. Used 18.50G of 15.48G hbm.\n"
        "Out of memory while trying to allocate 4294967296 bytes.\n"
        "Largest program allocations in hbm:\n"
        "  1. Size: 4.00G\n"
        "     Operator: op_name=\"jit(train_step)/jit(main)/dot_general\""
        " source_file=\"train.py\"\n"
        "     Shape: bf16[8,512,64,24]{3,2,1,0:T(8,128)(2,1)}\n"
        "     Unpadded size: 4.00G\n"
        "     Allocation type: HLO temp\n"
        "  2. Size: 512.00M\n"
        "     Operator: op_name=\"jit(train_step)/transpose\"\n"
        "     Shape: f32[128,1024,1024]{2,1,0}\n"
        "     Allocation type: HLO temp\n")
    rep = memory.parse_allocator_report(text)
    assert rep["requested_bytes"] == 4294967296
    assert len(rep["allocations"]) == 2
    a0 = rep["allocations"][0]
    assert a0["size_bytes"] == 4 * 10 ** 9
    assert "dot_general" in a0["operator"]
    assert a0["shape"].startswith("bf16[8,512,64,24]")
    assert a0["alloc_type"] == "HLO temp"
    assert rep["allocations"][1]["size_bytes"] == 512 * 10 ** 6
    # garbage degrades to an empty report, never a crash
    assert memory.parse_allocator_report("no report here") == {
        "requested_bytes": None, "allocations": []}


def test_is_oom_error_recognizes_injected_and_real():
    assert memory.is_oom_error(memory.synthetic_oom(7))
    assert memory.is_oom_error(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate ..."))
    assert memory.is_oom_error(RuntimeError("Ran out of memory: "
                                            "out of memory in hbm"))
    assert not memory.is_oom_error(RuntimeError("NaN loss"))
    assert not memory.is_oom_error(ValueError("bad shape"))


def test_chaos_oom_at_7_dumps_post_mortem_and_reraises(monkeypatch,
                                                      tmp_path):
    """THE acceptance gate: APEX_TPU_FAULTS="oom@7" under TrainGuard
    yields exactly one schema-valid flight-oom-*.json containing the
    attribution table and bad_step=7, and the run re-raises without
    burning a rollback retry."""
    monkeypatch.setenv("APEX_TPU_FAULTS", "oom@7")
    tr = trace.Tracer()
    trace.set_tracer(tr)
    sink = MemorySink()
    reg = Registry(sink=sink, flush_interval=0, rank0_only=False)
    # the registered static attribution (what a run computes up front)
    model = memory.memory_model(_opt_step, _opt_state(), jnp.ones((8, 64)))

    @jax.jit
    def step(w, batch):
        return w - 0.1 * batch, jnp.sum(w)

    g = TrainGuard(step, GuardConfig(ckpt_dir=str(tmp_path),
                                     save_every_steps=5, check_every=2,
                                     enabled=True),
                   registry=reg)
    with pytest.raises(memory.InjectedOomError):
        g.run(jnp.zeros(4),
              lambda i: jnp.asarray(np.random.RandomState(i)
                                    .randn(4).astype(np.float32)), 20)

    dumps = glob.glob(str(tmp_path / "flight-oom-*.json"))
    assert len(dumps) == 1                       # exactly one
    doc = json.load(open(dumps[0]))
    assert memory.oom_violations(doc) == []      # schema-valid
    assert doc["reason"] == "oom"
    assert doc["fields"]["bad_step"] == 7
    sec = doc["oom"]
    assert sec["bad_step"] == 7
    assert sec["error_type"] == "InjectedOomError"
    # the attribution table rode along
    assert sec["attribution"]["peak_hbm_bytes"] == model["peak_hbm_bytes"]
    assert sec["attribution"]["by_class"] == model["by_class"]
    # the synthetic allocator report parsed into structured allocations
    assert sec["requested_bytes"] == 2 ** 31
    assert sec["allocations"] and \
        sec["allocations"][0]["operator"] == "injected/oom/fault"
    # the ring names the injected fault at its step
    injected = [e for e in doc["entries"]
                if e["kind"] == "event" and e["name"] == "fault_injected"]
    assert [e["fields"]["step"] for e in injected] == [7]
    # no rollback retry burn: the guard re-raised instead of restoring
    reg.flush()
    evs = [r["name"] for r in sink.records if r.get("kind") == "event"]
    assert "rollback" not in evs
    assert "memory.oom" in evs
    s = report.summarize(sink.records)
    assert s["oom_events"] == 1 and s["rollbacks"] == 0
    assert "oom events 1" in report.format_summary(s)
    # no generic exception dump shadowing the post-mortem
    assert glob.glob(str(tmp_path / "flight-exception-*.json")) == []


def test_dump_oom_without_tracer_still_lands(tmp_path):
    """A crash artifact must not depend on tracing being on: the guard
    falls back to a fresh empty ring next to the checkpoints."""
    @jax.jit
    def step(w, batch):
        return w + batch, jnp.sum(w)

    g = TrainGuard(step, GuardConfig(ckpt_dir=str(tmp_path),
                                     check_every=4, enabled=True),
                   plan=faults.parse("oom@3"))
    with pytest.raises(memory.InjectedOomError):
        g.run(jnp.zeros(4), lambda i: jnp.ones(4), 10)
    dumps = glob.glob(str(tmp_path / "flight-oom-*.json"))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert memory.oom_violations(doc) == []
    assert doc["oom"]["bad_step"] == 3
    assert doc["n_entries"] == 0                 # untraced: empty ring


def test_real_resource_exhausted_text_takes_oom_path(tmp_path):
    """A step fn raising a REAL-shaped RESOURCE_EXHAUSTED (not the
    injected kind) still gets the post-mortem, not the generic dump."""
    msg = ("RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
           "1073741824 bytes.\n  1. Size: 1.00G\n"
           "     Operator: op_name=\"jit(step)/big_dot\"\n")

    def step(w, batch):
        raise RuntimeError(msg)

    g = TrainGuard(step, GuardConfig(ckpt_dir=str(tmp_path),
                                     check_every=4, enabled=True))
    with pytest.raises(RuntimeError):
        g.run(jnp.zeros(4), lambda i: jnp.ones(4), 10)
    dumps = glob.glob(str(tmp_path / "flight-oom-*.json"))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["oom"]["requested_bytes"] == 1073741824
    assert doc["oom"]["allocations"][0]["operator"] == "jit(step)/big_dot"
    assert glob.glob(str(tmp_path / "flight-exception-*.json")) == []


def test_faults_grammar_accepts_oom():
    plan = faults.parse("oom@7;nan@3")
    assert [s.kind for s in plan.specs] == ["oom", "nan"]
    assert plan.fire("oom", 6) is None
    assert plan.fire("oom", 7).kind == "oom"
    assert plan.fire("oom", 8) is None           # one-shot consumed


# ---------------------------------------------------------------------------
# the CLI (the acceptance's rendering gate)
# ---------------------------------------------------------------------------

def test_cli_mem_table_total_matches_liveness_sweep():
    """`python -m apex_tpu.telemetry mem` renders a per-class peak-HBM
    table whose total matches the liveness sweep on the flagship
    transformer step."""
    from apex_tpu.telemetry.report import demo_step_fn
    cfg = dict(layers=1, batch=2, seq=16)
    train_step, state, make_batch = demo_step_fn(**cfg)
    tokens, targets = make_batch(0)
    t = memory.memory_table(train_step, state, tokens, targets,
                            jnp.asarray(1.0, jnp.float32))
    assert sum(t["by_class"].values()) == t["peak_bytes"]
    # the flagship's O5 state classifies: bf16 model params, fp32
    # masters+moments as optimizer state, the token batch
    assert t["by_class"]["params"] > 0
    assert t["by_class"]["optimizer"] > t["by_class"]["params"]
    assert t["by_class"]["batch"] > 0

    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.telemetry", "mem",
         "--layers", "1", "--batch", "2", "--seq", "16"],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "peak-HBM attribution" in r.stdout
    assert "optimizer" in r.stdout and "activations" in r.stdout
    # the CLI's rendered total IS the sweep's peak for the same config
    expected = memory._human(t["peak_bytes"], "B")
    assert f"{expected} (= liveness-sweep peak)" in r.stdout
    assert "memory_model: peak" in r.stdout


def test_cli_mem_renders_oom_dump_and_bench_artifact(tmp_path):
    # an OOM dump round-trips through the renderer
    memory.set_attribution({"peak_hbm_bytes": 999,
                            "by_class": {"params": 999}})
    path = memory.dump_oom(step=7, error=memory.synthetic_oom(7),
                           directory=str(tmp_path))
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.telemetry", "mem", path],
        capture_output=True, text=True, cwd=ROOT, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "OOM post-mortem" in r.stdout
    assert "bad_step=7" in r.stdout

    # a bench artifact with per-leg fields renders the MFU/HBM table
    art = tmp_path / "bench.json"
    art.write_text(json.dumps({"detail": {"bert_e2e": {
        "mfu_pct": 41.2, "hbm_compiled_peak_bytes": 123456}}}))
    r2 = subprocess.run(
        [sys.executable, "-m", "apex_tpu.telemetry", "mem", str(art)],
        capture_output=True, text=True, cwd=ROOT, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT})
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "bert_e2e" in r2.stdout and "41.2" in r2.stdout
