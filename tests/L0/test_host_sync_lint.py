"""Host-sync lint (ISSUE 5 satellite): grep ``apex_tpu/`` for
``device_get`` / ``block_until_ready`` CALLS outside the sanctioned
modules, so new code can't silently add per-step host syncs.

The telemetry/resilience subsystems exist to BATCH host reads (one
``device_get`` per flush/check interval); a stray per-step sync anywhere
else voids that contract without failing any behavioral test.  This
lint makes the budget a tier-1 invariant.

Sanctioned call sites (each one is the documented batching point or an
inherently host-side boundary):

  * ``telemetry/registry.py``  — the single batched flush read
  * ``telemetry/events.py``    — the batched scaler-state read
  * ``telemetry/memory.py``    — the allocator poll at flush cadence
  * ``telemetry/timeline.py``  — offline profiler-dir parsing: its file
    reads happen in tooling/post-capture context, never inside a train
    step; sanctioned explicitly so future capture helpers that need a
    sync boundary (closing a profiler window flushes the device) have
    a documented home
  * ``telemetry/goodput.py``   — the run-level goodput ledger:
    sanctioned explicitly (ISSUE 15) even though it performs NO host
    syncs today — every number it touches is a host ``perf_counter``
    microsecond, and ``tests/L0/test_goodput.py`` asserts the disabled
    ledger does zero syncs and zero per-record allocation growth; the
    explicit row documents that any future sync added here must stay
    inside the registry-flush batching window
  * ``resilience/guard.py``    — the batched health-check/snapshot read
  * ``checkpoint.py``          — serialization is a host operation
  * ``interop/__init__.py``    — the torch bridge is host-side by design
  * ``pyprof/prof.py``         — measured timing must synchronize
  * ``serve/schedule.py``      — the continuous-batching scheduler's
    single per-decode-step boundary read (ISSUE 18): ONE batched
    ``device_get`` of the decode tokens + pending prefill tokens per
    step; all page-table, position, and admission bookkeeping is host
    arithmetic, so the step count — not the request count — bounds the
    syncs

A second, narrower budget covers ``device.memory_stats()`` (ISSUE 6):
allocator polling is a host read too, and it must stay batched at the
registry-flush cadence — so the ONLY module allowed to call it is
``telemetry/memory.py`` (``MemoryMonitor`` / ``device_memory_stats``).

Anything else needs either routing through the registry/guard batching
or an explicit ``# host-sync: ok`` waiver with a reason.
"""
import os
import re

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG = os.path.join(ROOT, "apex_tpu")

SANCTIONED = {
    os.path.join("telemetry", "registry.py"),
    os.path.join("telemetry", "events.py"),
    os.path.join("telemetry", "memory.py"),
    os.path.join("telemetry", "timeline.py"),
    os.path.join("telemetry", "goodput.py"),
    os.path.join("resilience", "guard.py"),
    "checkpoint.py",
    os.path.join("interop", "__init__.py"),
    os.path.join("pyprof", "prof.py"),
    os.path.join("serve", "schedule.py"),
}

#: allocator polling is its own, narrower budget: memory_stats() calls
#: belong ONLY in the memory module (registry.flush reaches them
#: through MemoryMonitor.observe_flush)
MEMSTATS_SANCTIONED = {
    os.path.join("telemetry", "memory.py"),
}

# a CALL, not a docstring mention: the name must be followed by "("
_SYNC_CALL = re.compile(r"\b(device_get|block_until_ready)\s*\(")
_MEMSTATS_CALL = re.compile(r"\b(memory_stats)\s*\(")
_WAIVER = "# host-sync: ok"


def _py_files():
    for dirpath, _dirs, files in os.walk(PKG):
        for name in files:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def test_no_host_syncs_outside_sanctioned_modules():
    offenders = []
    for path in _py_files():
        rel = os.path.relpath(path, PKG)
        if rel in SANCTIONED:
            continue
        with open(path) as f:
            for ln, line in enumerate(f, 1):
                m = _SYNC_CALL.search(line)
                if m and _WAIVER not in line:
                    offenders.append(f"apex_tpu/{rel}:{ln}: {m.group(1)} "
                                     f"call: {line.strip()[:80]}")
    assert offenders == [], (
        "per-step host syncs outside the sanctioned batching points "
        "(route the read through telemetry.Registry.flush / "
        "TrainGuard._health_check, or add an explicit "
        f"'{_WAIVER}' waiver with a reason):\n" + "\n".join(offenders))


def test_no_memory_stats_outside_memory_module():
    """The narrower allocator-poll budget (ISSUE 6): a stray
    ``memory_stats()`` anywhere but ``telemetry/memory.py`` is an
    unbatched host read the memory monitor exists to centralize."""
    offenders = []
    for path in _py_files():
        rel = os.path.relpath(path, PKG)
        if rel in MEMSTATS_SANCTIONED:
            continue
        with open(path) as f:
            for ln, line in enumerate(f, 1):
                m = _MEMSTATS_CALL.search(line)
                if m and _WAIVER not in line:
                    offenders.append(f"apex_tpu/{rel}:{ln}: {m.group(1)} "
                                     f"call: {line.strip()[:80]}")
    assert offenders == [], (
        "memory_stats() calls outside telemetry/memory.py (route the "
        "poll through telemetry.memory.MemoryMonitor / "
        "device_memory_stats, or add an explicit "
        f"'{_WAIVER}' waiver with a reason):\n" + "\n".join(offenders))


def test_lint_actually_detects_a_call(tmp_path):
    """The lint's regex matches real call syntax and skips docstring
    mentions — guard against the lint rotting into a tautology."""
    assert _SYNC_CALL.search("host = jax.device_get(arrays)")
    assert _SYNC_CALL.search("jax.block_until_ready (x)")
    assert not _SYNC_CALL.search("one ``jax.device_get`` per flush")
    assert not _SYNC_CALL.search("the device_get budget")
    assert _MEMSTATS_CALL.search("stats = device.memory_stats()")
    assert not _MEMSTATS_CALL.search("polls ``device.memory_stats`` data")


def test_sanctioned_files_exist():
    """A sanctioned path that no longer exists is stale lint config."""
    for rel in SANCTIONED | MEMSTATS_SANCTIONED:
        assert os.path.exists(os.path.join(PKG, rel)), rel


def test_fleet_and_export_are_covered_with_no_waiver():
    """ISSUE 20: the fleet merge and the live exporter promise ZERO
    host syncs (the export snapshot rides the registry flush's batched
    window; the fleet merge is pure file tooling).  They must be
    walked by the lint — present on disk, NOT sanctioned, and free of
    sync calls or waivers, so a future sync added to either fails
    ``test_no_host_syncs_outside_sanctioned_modules`` immediately."""
    for rel in (os.path.join("telemetry", "fleet.py"),
                os.path.join("telemetry", "export.py")):
        path = os.path.join(PKG, rel)
        assert os.path.exists(path), rel
        assert rel not in SANCTIONED, rel
        text = open(path).read()
        assert _WAIVER not in text, rel
        assert not _SYNC_CALL.search(text), rel
        assert not _MEMSTATS_CALL.search(text), rel
