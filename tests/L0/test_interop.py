"""DLPack/torch interop tests (north star: fused optimizers usable from a
torch loop).  torch (CPU) ships in the image; guarded anyway."""
import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")

from apex_tpu.interop import from_torch, to_torch, TorchFusedOptimizer
from apex_tpu.optimizers import FusedAdam, FusedSGD


def test_dlpack_round_trip():
    t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
    x = from_torch(t)
    assert isinstance(x, jnp.ndarray)
    np.testing.assert_array_equal(np.asarray(x), t.numpy())
    t2 = to_torch(x)
    np.testing.assert_array_equal(t2.numpy(), t.numpy())


@pytest.mark.parametrize("impl", ["xla", "fused"])
def test_torch_loop_matches_torch_adamw(impl):
    torch.manual_seed(0)
    model = torch.nn.Linear(8, 4)
    ref = torch.nn.Linear(8, 4)
    with torch.no_grad():
        ref.weight.copy_(model.weight)
        ref.bias.copy_(model.bias)

    opt = TorchFusedOptimizer(model.parameters(),
                              FusedAdam(lr=1e-2, weight_decay=0.01,
                                        impl=impl))
    ropt = torch.optim.AdamW(ref.parameters(), lr=1e-2, weight_decay=0.01,
                             eps=1e-8)
    x = torch.randn(16, 8)
    y = torch.randn(16, 4)
    for _ in range(5):
        opt.zero_grad()
        loss = (model(x) - y).pow(2).mean()
        loss.backward()
        opt.step()

        ropt.zero_grad()
        rloss = (ref(x) - y).pow(2).mean()
        rloss.backward()
        ropt.step()

    np.testing.assert_allclose(model.weight.detach().numpy(),
                               ref.weight.detach().numpy(), atol=1e-3)
    np.testing.assert_allclose(model.bias.detach().numpy(),
                               ref.bias.detach().numpy(), atol=1e-3)


def test_scale_and_explicit_grads():
    p = torch.nn.Parameter(torch.ones(4, 8))
    opt = TorchFusedOptimizer([p], FusedSGD(lr=0.1))
    g = torch.full((4, 8), 64.0)
    opt.step(grads=[g], scale=64.0)      # pre-scaled grads, scale divides
    np.testing.assert_allclose(p.detach().numpy(), np.ones((4, 8)) - 0.1,
                               rtol=1e-6)


def test_bf16_round_trip_fallback():
    """bf16 crossings must survive even when DLPack zero-copy is refused
    (the fp32 staging hop)."""
    t = torch.arange(8, dtype=torch.bfloat16)
    x = from_torch(t)
    assert x.dtype == jnp.bfloat16
    t2 = to_torch(jnp.asarray([1.5, 2.5], jnp.bfloat16))
    assert t2.dtype == torch.bfloat16
    np.testing.assert_array_equal(t2.float().numpy(), [1.5, 2.5])


@pytest.mark.parametrize("impl", ["xla", "fused"])
def test_torch_side_mutation_honored(impl):
    """Params loaded/mutated torch-side AFTER optimizer construction must be
    what the next step acts on (no stale snapshot)."""
    p = torch.nn.Parameter(torch.zeros(4, 8))
    opt = TorchFusedOptimizer([p], FusedSGD(lr=0.5, impl=impl))
    with torch.no_grad():
        p.copy_(torch.ones(4, 8))      # e.g. load_state_dict
    opt.step(grads=[torch.full((4, 8), 1.0)])
    np.testing.assert_allclose(p.detach().numpy(),
                               np.full((4, 8), 0.5), rtol=1e-6)


def test_state_dict_round_trip():
    p = torch.nn.Parameter(torch.ones(8, 8))
    opt = TorchFusedOptimizer([p], FusedAdam(lr=1e-2))
    p.grad = torch.full((8, 8), 0.5)
    opt.step()
    sd = opt.state_dict()
    val_after_1 = p.detach().clone()

    # continue two different ways: fresh-loaded vs original
    opt.step()
    val_after_2 = p.detach().clone()

    p2 = torch.nn.Parameter(val_after_1.clone())
    opt2 = TorchFusedOptimizer([p2], FusedAdam(lr=1e-2))
    opt2.load_state_dict(sd)
    p2.grad = torch.full((8, 8), 0.5)
    opt2.step()
    np.testing.assert_allclose(p2.detach().numpy(), val_after_2.numpy(),
                               atol=1e-6)


@pytest.mark.parametrize("impl", ["xla", "fused"])
def test_many_params_order_stable(impl):
    """>= 10 params: flatten order must follow the list, not lexicographic
    key order (regression for dict-keyed trees where p10 < p2).  impl='xla'
    exercises the generic tree path, impl='fused' (CPU fp32 contiguous) the
    native packed path."""
    torch.manual_seed(3)
    ps = [torch.nn.Parameter(torch.randn(3, 4) * (i + 1))
          for i in range(12)]
    ref = [p.detach().clone() for p in ps]
    opt = TorchFusedOptimizer(ps, FusedSGD(lr=0.1, impl=impl))
    grads = [torch.full((3, 4), float(i)) for i in range(12)]
    opt.step(grads=grads)
    for i, (p, r) in enumerate(zip(ps, ref)):
        np.testing.assert_allclose(p.detach().numpy(),
                                   (r - 0.1 * i).numpy(), atol=1e-6,
                                   err_msg=f"param {i}")


def test_non_contiguous_params_use_generic_path():
    """Non-contiguous CPU fp32 params must fall back to the generic path
    (the packed path requires contiguity) and still train correctly."""
    base = torch.randn(4, 8)
    p = torch.nn.Parameter(base.t())          # non-contiguous view
    assert not p.is_contiguous()
    opt = TorchFusedOptimizer([p], FusedSGD(lr=0.5, impl="fused"))
    before = p.detach().clone()
    opt.step(grads=[torch.ones(8, 4)])
    np.testing.assert_allclose(p.detach().numpy(),
                               (before - 0.5).numpy(), rtol=1e-6)


def test_native_host_pack_round_trip():
    from apex_tpu.utils import host_pack
    arrays = [np.random.RandomState(i).randn(n).astype(np.float32)
              for i, n in enumerate([5, 128, 300])]
    offsets = [0, 128, 256]      # 128-aligned, 256+300 <= 640
    total = 640
    flat = host_pack.pack(arrays, offsets, total)
    assert flat.shape == (total,)
    for a, off in zip(arrays, offsets):
        np.testing.assert_array_equal(flat[off:off + a.size], a)
    # padding gap stays zero
    assert (flat[5:128] == 0).all()
    outs = [np.zeros_like(a) for a in arrays]
    host_pack.unpack(flat, outs, offsets)
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)
    # the native library should have compiled in this image (g++ baked in)
    assert host_pack.native_available()
    # invalid layouts raise instead of corrupting the heap
    with pytest.raises(ValueError):
        host_pack.pack(arrays, [0, 128, 400], total)
    with pytest.raises(ValueError):
        host_pack.unpack(flat, outs, [0, 128, 400])


def test_hyperparam_mutation_invalidates_jit_cache():
    """step math is jitted (round 5); a torch-style in-place mutation of
    a hyperparameter between steps must retrace, not be baked in from
    the first trace (code-review r5)."""
    import torch
    from apex_tpu.interop import TorchFusedOptimizer
    from apex_tpu.optimizers import FusedSGD

    p = torch.nn.Parameter(torch.zeros(8, 4))
    opt = TorchFusedOptimizer([p], FusedSGD(lr=0.5, impl="fused"))
    opt.step(grads=[torch.ones(8, 4)])
    np.testing.assert_allclose(p.detach().numpy(), np.full((8, 4), -0.5),
                               rtol=1e-6)
    opt.optimizer.lr = 0.25                    # honored by the eager path
    opt.step(grads=[torch.ones(8, 4)])
    np.testing.assert_allclose(p.detach().numpy(), np.full((8, 4), -0.75),
                               rtol=1e-6)


def test_pack_out_reuse_and_validation():
    from apex_tpu.utils import host_pack
    arrays = [np.full((4,), 7.0, np.float32)]
    out = np.zeros((128,), np.float32)
    flat = host_pack.pack(arrays, [0], 128, out=out)
    assert flat is out and (out[:4] == 7.0).all() and (out[4:] == 0).all()
    # reuse: spans overwritten, gaps untouched (still zero)
    arrays2 = [np.full((4,), 3.0, np.float32)]
    host_pack.pack(arrays2, [0], 128, out=out)
    assert (out[:4] == 3.0).all() and (out[4:] == 0).all()
    with pytest.raises(ValueError):
        host_pack.pack(arrays, [0], 64, out=out)          # wrong shape
    with pytest.raises(ValueError):
        host_pack.pack(arrays, [0], 128, dtype=np.float64,
                       out=out)                           # wrong dtype
