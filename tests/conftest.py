"""Test harness: force an 8-device CPU platform so multi-chip SPMD paths are
exercised without TPU hardware (the capability called out in SURVEY §4 —
``xla_force_host_platform_device_count`` gives N-device SPMD on CPU, which the
reference's real-multiprocess test harness could not do).

All platform-forcing logic (env flags, config update, dropping the
single-client axon TPU-tunnel backend factory so enumeration can never dial
and hang on it) lives in ``apex_tpu.utils.platform.force_cpu`` — shared with
the driver entry points so tunnel fixes land in exactly one place.
Importing apex_tpu imports jax but does NOT initialize a backend, so calling
``force_cpu`` right after import is still early enough; it also resets an
already-initialized wrong backend defensively.
"""
from apex_tpu.utils.platform import force_cpu

force_cpu(8)

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(autouse=True)
def _amp_unpatch():
    """Keep autocast patches from leaking between tests."""
    yield
    from apex_tpu.amp import amp as _amp
    if _amp.is_initialized():
        _amp.uninit()
