"""Test harness: force an 8-device CPU platform so multi-chip SPMD paths are
exercised without TPU hardware (the capability called out in SURVEY §4 —
``xla_force_host_platform_device_count`` gives N-device SPMD on CPU, which the
reference's real-multiprocess test harness could not do)."""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# Force CPU: the ambient environment may set JAX_PLATFORMS=axon (the real TPU
# tunnel, single-client) — tests must never contend for the chip.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (import after env setup)

# A sitecustomize hook may have imported jax already (registering a TPU-tunnel
# "axon" plugin), in which case the env var above came too late — force the
# platform through the config API, and drop the axon factory so backend
# enumeration can never dial (and hang on) the tunnel from the test suite.
jax.config.update("jax_platforms", "cpu")
try:  # pragma: no cover - environment-specific
    from jax._src import xla_bridge as _xb
    getattr(_xb, "_backend_factories", {}).pop("axon", None)
except Exception:
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(autouse=True)
def _amp_unpatch():
    """Keep autocast patches from leaking between tests."""
    yield
    from apex_tpu.amp import amp as _amp
    if _amp.is_initialized():
        _amp.uninit()
