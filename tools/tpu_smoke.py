#!/usr/bin/env python
"""Mosaic first-contact smoke: compile every Pallas kernel family at one
production shape and assert numerics against the XLA reference, in under
60 s of chip time (VERDICT next-round #7).

Run by ``tpu_watch.sh`` as the FIRST capture stage: a chip/toolchain
combination that cannot compile-and-match the kernels is not worth
burning a recovery window on — the watcher logs the failure and resumes
probing.  On CPU the same checks run in Pallas interpret mode at small
shapes (``--tiny``), so the harness logic has a tier-1 test without a
chip (``tests/L0/test_tpu_smoke.py``).

Always prints exactly ONE JSON line on stdout::

    {"smoke": "pallas_numerics", "backend": "tpu", "tiny": false,
     "elapsed_s": 41.3, "passed": {"flash_fwd": {...}, ...},
     "failed": {"xentropy": "XlaRuntimeError(...)"}}

exit 0 iff nothing failed.  ``--only a,b`` restricts the check set.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _rel_err(got, want):
    import jax.numpy as jnp
    got = jnp.asarray(got, jnp.float32)
    want = jnp.asarray(want, jnp.float32)
    denom = float(jnp.max(jnp.abs(want))) or 1.0
    return float(jnp.max(jnp.abs(got - want))) / denom


def _tree_rel_err(got, want):
    import jax
    return max(_rel_err(g, w) for g, w in
               zip(jax.tree_util.tree_leaves(got),
                   jax.tree_util.tree_leaves(want)))


# ---------------------------------------------------------------------------
# checks — each returns the max relative error of pallas vs XLA.  Shapes:
# (production, tiny); production = the flagship regimes the benches run.
# ---------------------------------------------------------------------------

def check_flash_fwd(tiny):
    import jax
    import jax.numpy as jnp
    from apex_tpu.contrib.multihead_attn import flash as F
    BH, S, D = (2, 128, 64) if tiny else (64, 512, 64)
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (BH, S, D), jnp.bfloat16) * 0.1
    k = jax.random.normal(k2, (BH, S, D), jnp.bfloat16) * 0.1
    v = jax.random.normal(k3, (BH, S, D), jnp.bfloat16) * 0.1
    bias = jnp.zeros((1, 1, S), jnp.float32)
    got = jax.jit(lambda a, b, c: F.flash_attention(
        a, b, c, bias, causal=True, heads=1))(q, k, v)
    want = F._xla_reference(q, k, v, bias, True, 0.0, 0, 1)
    return _rel_err(got, want)


def check_flash_bwd(tiny):
    import jax
    import jax.numpy as jnp
    from apex_tpu.contrib.multihead_attn import flash as F
    BH, S, D = (2, 128, 64) if tiny else (64, 512, 64)
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(k1, (BH, S, D), jnp.bfloat16) * 0.1
    k = jax.random.normal(k2, (BH, S, D), jnp.bfloat16) * 0.1
    v = jax.random.normal(k3, (BH, S, D), jnp.bfloat16) * 0.1
    bias = jnp.zeros((1, 1, S), jnp.float32)

    def loss(backward):
        return jax.jit(jax.grad(
            lambda a, b, c: F.flash_attention(
                a, b, c, bias, causal=True, heads=1,
                backward=backward).astype(jnp.float32).sum(),
            argnums=(0, 1, 2)))
    got = loss("pallas")(q, k, v)
    want = loss("xla")(q, k, v)
    return _tree_rel_err(got, want)


def check_xentropy(tiny):
    import jax
    import jax.numpy as jnp
    from apex_tpu.contrib.xentropy import softmax_xentropy_loss
    N, H = (64, 512) if tiny else (2048, 8192)
    logits = jax.random.normal(jax.random.PRNGKey(2), (N, H), jnp.float32)
    labels = jax.random.randint(jax.random.PRNGKey(3), (N,), 0, H)

    def run(impl):
        f = lambda lg: softmax_xentropy_loss(lg, labels, smoothing=0.1,
                                             impl=impl).sum()
        return jax.jit(jax.value_and_grad(f))(logits)
    (lp, gp), (lx, gx) = run("pallas"), run("xla")
    return max(_rel_err(lp, lx), _rel_err(gp, gx))


def check_layer_norm(tiny):
    import jax
    import jax.numpy as jnp
    from apex_tpu.normalization import fused_layer_norm_affine
    N, H = (64, 256) if tiny else (4096, 1024)
    x = jax.random.normal(jax.random.PRNGKey(4), (N, H), jnp.float32)
    w = jnp.ones((H,)) * 1.1
    b = jnp.zeros((H,)) + 0.1

    def run(use_pallas):
        f = lambda x_, w_, b_: fused_layer_norm_affine(
            x_, w_, b_, (H,), use_pallas=use_pallas).sum()
        return jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))(x, w, b)
    (lp, gp), (lx, gx) = run(True), run(False)
    return max(_rel_err(lp, lx), _tree_rel_err(gp, gx))


def check_mlp(tiny):
    import jax
    import jax.numpy as jnp
    from apex_tpu.ops.fused_mlp import dense_act
    M, K, N = (64, 128, 256) if tiny else (1024, 1024, 4096)
    x = jax.random.normal(jax.random.PRNGKey(5), (M, K), jnp.float32) * 0.1
    w = jax.random.normal(jax.random.PRNGKey(6), (K, N), jnp.float32) * 0.1
    b = jnp.zeros((N,)) + 0.05
    got = jax.jit(lambda a, c, d: dense_act(a, c, d, "relu"))(x, w, b)
    want = jnp.maximum(x @ w + b, 0.0)
    return _rel_err(got, want)


def check_vmem_budget(tiny):
    """Compiled-footprint gate for the flash kernel family (ISSUE 6
    satellite: the ``_clamp_blocks`` budget model has been unvalidated
    since round 4): resolve the block sizes every kernel variant would
    actually run with (env pin > tuning profile > built-in, exactly the
    ``_clamp_blocks`` chain) at the production regime (D=64, bf16, seq
    512) and assert the per-grid-step VMEM estimate fits the budget the
    clamp enforces.  Returns the worst used/budget ratio — the check
    fails when any variant's resolved config models over budget, i.e.
    when the clamp loop and the footprint model have drifted apart.
    Pure estimator math (no compile), so the tiny tier-1 variant runs
    the identical check."""
    import os
    from apex_tpu.contrib.multihead_attn import flash as F
    budget = float(os.environ.get("APEX_TPU_FLASH_VMEM_MB",
                                  F._VMEM_BUDGET_MB)) * 2 ** 20
    D = 64
    sq = sk = 128 if tiny else 512
    worst = 0.0
    # every kernel variant x dtype x bias layout the clamp chain serves
    for bwd in (False, "dq", "dkv", "fused", True):
        for esz in (2, 4):                    # bf16 / f32 streams
            for bias_per_q in (False, True):
                bq, bk = F._clamp_blocks(None, None, D, esz, bias_per_q,
                                         bwd=bwd, sq=sq, sk=sk)
                est = F.vmem_estimate(bq, bk, D, esz, bias_per_q, bwd)
                worst = max(worst, est / budget)
    return worst


def check_spmd_compile(tiny):
    """SPMD step-engine compile smoke (ISSUE 12, pp/ep per ISSUE 17):
    every plan family — dp x tp (GSPMD jit), dp x sp ring, dp x sp
    ulysses, dp x pp (GPipe stages), dp x ep (switch-MoE experts),
    zero1 update sharding, contrib ZeRO — builds and runs one tiny
    train step on a 2x2 mesh (4 devices; smaller device counts degrade
    to the factorizations that fit).  Value is the count of families
    that failed to build/run (0.0 = all compiled); a toolchain where a
    family's engine cannot even compile must fail the smoke before a
    capture window is spent measuring it.  The tiny and production
    variants run the same logic — the engine's cost is compile time,
    not shape-dependent numerics."""
    import jax
    import jax.numpy as jnp
    from apex_tpu.models import TransformerConfig
    from apex_tpu.parallel import plan as pm
    from apex_tpu.parallel import spmd

    n = len(jax.devices())
    # two layers so a 2-stage pipeline divides evenly; all other
    # families are layer-count agnostic
    cfg = TransformerConfig(vocab_size=64, max_len=16, num_layers=2,
                            d_model=32, num_heads=2, d_ff=64,
                            xent_impl="xla")
    gb = 4
    plans = []
    if n >= 4:
        plans += [pm.Plan(dp=2, tp=2),
                  pm.Plan(dp=2, sp=2, sp_strategy="ring"),
                  pm.Plan(dp=2, sp=2, sp_strategy="ulysses"),
                  pm.Plan(dp=2, pp_stages=2, pp_microbatches=2),
                  pm.Plan(dp=2, ep=2),
                  pm.Plan(dp=4, update_sharding="zero1"),
                  pm.Plan(dp=4, zero=True)]
    elif n >= 2:
        plans += [pm.Plan(dp=2, update_sharding="zero1"),
                  pm.Plan(dp=2, zero=True)]
    else:            # single chip: the dp engine is the only family
        plans += [pm.Plan(dp=1)]
    failed = 0
    toks = jnp.zeros((gb, cfg.max_len), jnp.int32)
    for p in plans:
        try:
            with p.apply(jax.devices()[: p.chips]) as mesh:
                carry, step, _info = spmd.build_plan_step(
                    cfg, mesh, p, global_batch=gb, meter=False)
                _, loss = step(carry, toks)
                if not bool(jnp.isfinite(loss)):
                    failed += 1
        except Exception:
            failed += 1
    return float(failed)


def check_multi_tensor(tiny):
    import jax.numpy as jnp
    import numpy as np
    from apex_tpu.multi_tensor_apply import (multi_tensor_axpby,
                                             multi_tensor_l2norm,
                                             multi_tensor_scale)
    total = 4096 if tiny else 4 * 1024 * 1024
    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.randn(total).astype(np.float32))
    ys = jnp.asarray(rng.randn(total).astype(np.float32))
    scaled, _flag = multi_tensor_scale(xs, 0.5)
    axpby, _flag = multi_tensor_axpby(xs, ys, 2.0, -0.5)
    errs = [
        _rel_err(scaled, xs * 0.5),
        _rel_err(axpby, 2.0 * xs - 0.5 * ys),
        _rel_err(multi_tensor_l2norm(xs),
                 jnp.sqrt(jnp.sum(xs.astype(jnp.float32) ** 2))),
    ]
    return max(errs)


def check_serve_compile(tiny):
    """Serving-engine compile smoke (ISSUE 18): every inference O-level
    (fp32 / bf16 / int8 block-scaled weights) builds an
    ``InferenceEngine`` over the paged KV cache and runs one prefill +
    one batched decode step on a tiny config.  Value is the count of
    O-levels that failed to build/run or produced a non-finite /
    out-of-range token (0.0 = all compiled); a toolchain where the
    serving engine cannot compile must fail the smoke before a serve
    A/B window is spent measuring it.  Tiny and production variants run
    the same logic — the cost is compile time, not shape-dependent
    numerics."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from apex_tpu.models import TransformerConfig, transformer_init
    from apex_tpu.serve import (CacheConfig, InferenceEngine, OLEVELS,
                                Request, ContinuousBatcher)

    cfg = TransformerConfig(vocab_size=64, max_len=32, num_layers=2,
                            d_model=32, num_heads=2, d_ff=64,
                            causal=True, xent_impl="xla")
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    cache = CacheConfig(page_size=8, num_pages=16, max_ctx=32)
    failed = 0
    for olevel in OLEVELS:
        try:
            eng = InferenceEngine(params, cfg, cache=cache,
                                  olevel=olevel, decode_width=2)
            bat = ContinuousBatcher(eng)
            bat.submit(Request(rid=f"smoke-{olevel}",
                               prompt=(1, 2, 3, 4), max_new_tokens=2))
            bat.run(max_steps=16)
            res = bat.results[f"smoke-{olevel}"]
            toks = np.asarray(res.tokens)
            if (res.status != "done" or len(res.tokens) != 2
                    or not bool(jnp.all((toks >= 0)
                                        & (toks < cfg.vocab_size)))):
                failed += 1
        except Exception:
            failed += 1
    return float(failed)


def check_control(tiny):
    """Run-controller smoke (ISSUE 19): arm a
    ``apex_tpu.control.RunController``, evaluate windows over injected
    signals, and fire one no-op-safe ``comm_retune`` action on the CPU
    mesh — in-band windows must stay silent, a K-consecutive breach
    must flip the live collective override one ladder rung, and the
    resulting ``CONTROL.json`` doc must pass its own schema.  Value is
    the failure count (0.0 = controller arms, gates, acts, audits);
    the live override is restored either way.  Tiny and production
    variants run the same logic — the controller is host arithmetic."""
    from apex_tpu.control import (ControlConfig, RunController,
                                  control_violations, default_policies)
    from apex_tpu.parallel import collectives as coll

    failed = 0
    prev_live = coll.get_live_spec()
    try:
        ctl = RunController(ControlConfig(enabled=True, max_actions=1),
                            policies=default_policies())
        ctl.arm(live_world=8)
        # window 1: everything in-band (exactly AT the ceiling counts
        # as in-band — the no-flap edge) -> no decisions
        if ctl.on_window(step=1, signals={"exposed_comm_fraction": 0.25,
                                          "goodput_fraction": 0.9}):
            failed += 1
        # windows 2..3: exposed-comm breach for k_consecutive=2 ->
        # exactly one acted comm_retune, fp32 -> bf16 live
        coll.set_live_spec(None)
        decisions = []
        for w in (2, 3):
            decisions += ctl.on_window(
                step=w, signals={"exposed_comm_fraction": 0.6,
                                 "goodput_fraction": 0.9})
        acted = [d for d in decisions if d["outcome"] == "acted"]
        if len(acted) != 1 or acted[0]["action"] != "comm_retune":
            failed += 1
        live = coll.get_live_spec()
        if live is None or live.scheme != "bf16":
            failed += 1
        doc = ctl.snapshot(status="completed")
        if control_violations(doc) or doc["actions_fired"] != 1:
            failed += 1
    except Exception:
        failed += 1
    finally:
        coll.set_live_spec(prev_live)
    return float(failed)


def check_export(tiny):
    """Live-export smoke (ISSUE 20): start a
    ``telemetry.export.MetricsExporter`` on an ephemeral port, flush a
    registry through it, scrape ``/metrics``, and shut it down clean —
    the endpoint must serve a parseable OpenMetrics snapshot carrying
    the flushed gauge value, and closing must join the daemon thread.
    Value is the failure count (0.0 = bind, snapshot, scrape, parse,
    shutdown all hold).  Host-only: no device work, same logic tiny
    and production."""
    import threading
    import urllib.request
    from apex_tpu.telemetry import MemorySink, Registry
    from apex_tpu.telemetry import export as _export

    failed = 0
    threads_before = threading.active_count()
    exp = _export.MetricsExporter(port=0, run_id="smoke").start()
    try:
        reg = Registry(sink=MemorySink(), enabled=True, flush_interval=0,
                       exporter=exp)
        reg.gauge("smoke.value").set(42.5)
        reg.event("smoke.event", ok=1)
        reg.flush()
        body = urllib.request.urlopen(exp.url, timeout=10).read().decode()
        lines = [ln for ln in body.splitlines() if ln.strip()]
        if not lines or lines[-1] != "# EOF":
            failed += 1
        samples = {}
        for ln in lines:
            if ln.startswith("#"):
                continue
            parts = ln.rsplit(None, 1)
            if len(parts) != 2:
                failed += 1
                break
            try:
                samples[parts[0]] = float(parts[1])
            except ValueError:
                failed += 1
                break
        if samples.get("apex_tpu_smoke_value") != 42.5:
            failed += 1
        if samples.get('apex_tpu_events_total{name="smoke_event"}') != 1:
            failed += 1
    except Exception:
        failed += 1
    finally:
        exp.close()
    if threading.active_count() > threads_before:
        failed += 1   # the daemon thread must be joined, not leaked
    return float(failed)


# check name -> (fn, relative-error tolerance).  bf16 kernels compare
# bf16-vs-bf16 math but accumulate differently (blocked f32 partials vs
# one einsum), hence the looser flash tolerances.
CHECKS = {
    "flash_fwd": (check_flash_fwd, 3e-2),
    "flash_bwd": (check_flash_bwd, 5e-2),
    "xentropy": (check_xentropy, 1e-4),
    "layer_norm": (check_layer_norm, 1e-4),
    "mlp": (check_mlp, 1e-4),
    "multi_tensor": (check_multi_tensor, 1e-5),
    # not a numerics check: the value is the worst used/budget VMEM
    # ratio over the flash kernel variants — 1.0 is the budget line
    "vmem_budget": (check_vmem_budget, 1.0),
    # not a numerics check: the value is the count of SPMD plan
    # families that failed to compile+run a tiny step — 0 required
    # (tol 0.5 admits only the zero count)
    "spmd_compile": (check_spmd_compile, 0.5),
    # not a numerics check: the value is the count of serving O-levels
    # whose engine failed to compile+run prefill/decode — 0 required
    "serve_compile": (check_serve_compile, 0.5),
    # not a numerics check: the value is the count of run-controller
    # contract failures (arm/gate/act/audit) — 0 required
    "control": (check_control, 0.5),
    # not a numerics check: the value is the count of live-export
    # contract failures (bind/snapshot/scrape/parse/shutdown) — 0
    # required
    "export": (check_export, 0.5),
}


def run_checks(tiny: bool = False, only=None) -> dict:
    """Run the check set and return the result payload (no printing)."""
    import jax
    t_start = time.monotonic()
    names = list(CHECKS) if not only else [n for n in CHECKS if n in only]
    passed = {}
    failed = {}
    for name in names:
        fn, tol = CHECKS[name]
        t0 = time.monotonic()
        try:
            err = fn(tiny)
            rec = {"rel_err": round(err, 6), "tol": tol,
                   "s": round(time.monotonic() - t0, 2)}
            if err <= tol:
                passed[name] = rec
            else:
                failed[name] = f"rel_err {err:.3e} > tol {tol:.0e}"
        except Exception as e:
            failed[name] = repr(e)[:200]
    return {
        "smoke": "pallas_numerics",
        "backend": jax.default_backend(),
        "tiny": bool(tiny),
        "elapsed_s": round(time.monotonic() - t_start, 2),
        "passed": passed,
        "failed": failed,
    }


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tiny", action="store_true",
                    help="small shapes (CPU interpret-mode tier-1 test)")
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of checks")
    args = ap.parse_args(argv)
    only = set(args.only.split(",")) if args.only else None
    if only is not None:
        unknown = only - set(CHECKS)
        if unknown:
            print(json.dumps({"smoke": "pallas_numerics",
                              "failed": {"cli": f"unknown checks "
                                                f"{sorted(unknown)}"},
                              "passed": {}}))
            return 2
    out = run_checks(tiny=args.tiny, only=only)
    print(json.dumps(out))
    return 0 if not out["failed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
