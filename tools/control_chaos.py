#!/usr/bin/env python
"""The run-controller chaos acceptance as a one-shot artifact (ISSUE 19).

Run by ``tpu_watch.sh`` stage 3c: train the flagship-shaped transformer
N-way under TrainGuard with a ``straggler@K:F`` fault armed and an
``apex_tpu.control.RunController`` riding the health-check window.  The
leave-one-out z-score must name the slowed device persistently, the
controller's quarantine policy must fire a synthesized ``resize@N:N-1``
through the guard, the run must come back up (N-1)-way through the
elastic reshard, and the final params must be BITWISE-identical to an
independent import of the post-quarantine checkpoint stepped forward
without any controller/elastic code.  The decision trail must survive
as a schema-valid ``CONTROL.json`` with >= 1 quarantine decision.

Prints exactly ONE JSON line on stdout::

    {"metric": "control_chaos", "backend": "cpu", "from_world": 8,
     "to_world": 7, "quarantine_decisions": 1, "control_valid": true,
     "quarantined_device": "d0", "bitwise": true, "elapsed_s": 41.0}

exit 0 iff the acceptance holds.  CPU runs the same logic on the forced
8-device host platform; the tool exists to capture the SAME proof on
real silicon through the watcher.
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build(world, cfg, su, global_batch):
    # the elastic_proof zero1 harness: flat sharded optimizer state so
    # the 8->7 reshard crosses a genuinely non-divisible chunk lattice
    import jax
    from jax.sharding import PartitionSpec as P
    from apex_tpu.models import transformer_init, transformer_loss
    from apex_tpu.parallel import create_mesh
    from apex_tpu.parallel.mesh import shard_map
    from apex_tpu.utils.pallas import has_vma, _to_varying

    mesh = create_mesh({"data": world}, jax.devices()[:world])
    params0 = transformer_init(jax.random.PRNGKey(0), cfg)
    vma_kw = {} if has_vma() else {"check_vma": False}
    pspec = jax.tree_util.tree_map(lambda _: P(), params0)
    sspec = su.state_pspecs(params0, world)

    @functools.partial(shard_map, mesh=mesh, in_specs=(pspec,),
                       out_specs=sspec)
    def init_s(p):
        return su.init(p)

    def body(params, state, tokens):
        pv = jax.tree_util.tree_map(
            lambda p: _to_varying(p, ("data",)), params)
        loss, grads = jax.value_and_grad(lambda p: transformer_loss(
            p, {"tokens": tokens, "targets": tokens}, cfg))(pv)
        params, state = su.step(state, grads, params)
        return params, state, jax.lax.pmean(loss, "data")

    jstep = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(pspec, sspec, P("data")),
        out_specs=(pspec, sspec, P()), **vma_kw))
    state0 = jax.jit(init_s)(params0)

    def step_fn(state, batch):
        params, opt_state = state
        params, opt_state, loss = jstep(params, opt_state, batch)
        return (params, opt_state), loss

    return (params0, state0), step_fn, su.layout_meta(params0, world)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--from-world", type=int, default=None,
                    help="chip count of the straggler-afflicted run "
                         "(default: all visible devices, max 8)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--straggle-at", type=int, default=2,
                    help="first step the straggler fault is armed at")
    ap.add_argument("--factor", type=float, default=4.0,
                    help="straggler slowdown factor F")
    ap.add_argument("--ckpt-dir", default=None)
    args = ap.parse_args(argv)

    t0 = time.time()
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    import apex_tpu.elastic as elastic
    from apex_tpu.control import (ControlConfig, RunController,
                                  control_violations)
    from apex_tpu.models import TransformerConfig
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import weight_update as wu
    from apex_tpu.resilience import (CheckpointManager, GuardConfig,
                                     TrainGuard, faults)
    from apex_tpu.telemetry import trace as ttrace

    backend = jax.default_backend()
    n_dev = len(jax.devices())
    from_world = args.from_world or min(8, n_dev)
    to_world = from_world - 1
    if from_world > n_dev or from_world < 2:
        print(json.dumps({"metric": "control_chaos", "backend": backend,
                          "error": f"need >= 2 devices (have {n_dev})"}))
        return 1

    cfg = TransformerConfig(vocab_size=64, max_len=20, num_layers=1,
                            d_model=32, num_heads=2, d_ff=64,
                            dtype=jnp.float32)
    global_batch = int(np.lcm(from_world, to_world))

    def make_batch(step):
        rng = np.random.RandomState(1000 + step)
        return jnp.asarray(
            rng.randint(0, 64, (global_batch, 20)).astype("int32"))

    def mk_su():
        return wu.ShardedUpdate(FusedAdam(lr=1e-2, impl="fused"),
                                axis_name="data")

    state_n, step_n, layout_n = _build(from_world, cfg, mk_su(),
                                       global_batch)
    state_m, step_m, layout_m = _build(to_world, cfg, mk_su(),
                                       global_batch)

    d = args.ckpt_dir or tempfile.mkdtemp(prefix="apex_tpu_control_")

    def gcfg(world, layout):
        return GuardConfig(ckpt_dir=d, save_every_steps=2, check_every=2,
                           backoff_seconds=0.01, enabled=True,
                           world_size=world,
                           ckpt_meta={"plan": {"dp": world},
                                      "layout": layout})

    # phase 1: the afflicted run — a persistent straggler the
    # controller must quarantine (the fault stays armed for the whole
    # run; the z-score needs >= 2 consecutive windows to name it)
    plan = faults.parse(
        f"straggler@{args.straggle_at}x{args.steps}:{args.factor}")
    tracer = ttrace.Tracer(enabled=True, flight_dir=d)
    prev_tracer = ttrace.set_tracer(tracer)
    try:
        ctl = RunController(ControlConfig(enabled=True, max_actions=2))
        _, r1 = TrainGuard(step_n, gcfg(from_world, layout_n), plan=plan,
                           controller=ctl).run(state_n, make_batch,
                                               args.steps)
    finally:
        ttrace.set_tracer(prev_tracer)

    doc = r1.control or {}
    quarantines = [dec for dec in doc.get("decisions", ())
                   if dec.get("action") == "quarantine"
                   and dec.get("outcome") == "acted"]
    control_valid = bool(doc) and not control_violations(doc)
    artifact_ok = bool(r1.control_path
                       and os.path.basename(r1.control_path)
                       == "CONTROL.json" and os.path.exists(r1.control_path))
    ok_quarantine = (r1.status == "preempted"
                     and r1.resize_to == to_world and len(quarantines) >= 1)
    quarantined = (quarantines[0]["detail"].get("device")
                   if quarantines and isinstance(
                       quarantines[0].get("detail"), dict) else None)

    # independent import of the post-quarantine checkpoint: reshard
    # through elastic ONCE into the (N-1)-way template, then step it
    # forward with plain engine code — no guard, no controller
    ck_step, payload, meta = CheckpointManager(d).load_latest(
        with_meta=True)
    payload_b = elastic.reshard_payload(state_m, payload, meta, to_world)
    import apex_tpu.resilience.guard as guard_mod
    state_b = guard_mod.TrainGuard(step_m, GuardConfig(enabled=True),
                                   )._restore(state_m, payload_b)
    for i in range(ck_step, args.steps):
        state_b, _ = step_m(state_b, make_batch(i))

    # phase 2: the real resumed run through the guard's elastic path
    state_a, r2 = TrainGuard(step_m, gcfg(to_world, layout_m),
                             elastic=elastic.ElasticResume()).run(
        state_m, make_batch, args.steps)

    bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(state_a),
                        jax.tree_util.tree_leaves(state_b)))
    out = {
        "metric": "control_chaos", "backend": backend,
        "from_world": from_world, "to_world": to_world,
        "steps": args.steps, "ckpt_step": int(ck_step),
        "kill_status": r1.status, "resize_to": r1.resize_to,
        "quarantine_decisions": len(quarantines),
        "quarantined_device": quarantined,
        "control_valid": bool(control_valid),
        "control_artifact": r1.control_path,
        "artifact_ok": bool(artifact_ok),
        "windows": doc.get("windows", 0),
        "resumed_from": r2.resumed_from,
        "resharded_from": r2.resharded_from,
        "bitwise": bool(bitwise),
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if (ok_quarantine and control_valid and artifact_ok
                 and bitwise and r2.resharded_from == from_world) else 1


if __name__ == "__main__":
    raise SystemExit(main())
