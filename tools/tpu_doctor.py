#!/usr/bin/env python
"""TPU tunnel doctor — one command for the docs/tpu_tunnel.md runbook.

Runs every locally-actionable diagnostic in order and prints a verdict:

1. leaked-client scan: any local process holding a connection to the
   relay (the ONLY locally-fixable wedge cause — kill it and re-probe);
2. relay TCP fingerprint: connect to 127.0.0.1:2024 and classify
   (refused / accept-then-EOF / banner) — accept-then-EOF means the
   relay's upstream is gone and no client-side action can help;
3. subprocess health probe (`probe_ambient_backend`) with failure detail;
4. watcher status (tpu_watch.sh running? last log lines).

Exit code 0 iff the tunnel is healthy.  Never dials the tunnel
in-process (a wedged dial blocks in C++ and cannot be interrupted).

Usage:  python tools/tpu_doctor.py [--probe-timeout 75]
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

RELAY = ("127.0.0.1", 2024)


def leaked_clients():
    """PIDs with an established connection to the relay (via /proc).

    Returns ``(hits, note)``: ``note`` is non-empty when the scan could
    not run (no iproute2 ``ss`` on this host) — the doctor's later steps
    (fingerprint / probe / watcher) must still execute in that case."""
    try:
        out = subprocess.run(["ss", "-tnp"], capture_output=True, text=True)
    except (FileNotFoundError, OSError) as e:
        return [], f"scan unavailable ({e.__class__.__name__}: {e})"
    hits = []
    for line in (out.stdout or "").splitlines():
        if f"{RELAY[0]}:{RELAY[1]}" in line and "ESTAB" in line:
            hits.append(line.strip())
    return hits, ""


def relay_fingerprint():
    try:
        s = socket.create_connection(RELAY, timeout=3)
    except OSError as e:
        return "refused", f"TCP connect failed: {e}"
    try:
        s.settimeout(2)
        try:
            data = s.recv(256)
        except socket.timeout:
            return "open-silent", "TCP open, no banner within 2s (normal " \
                                  "for a healthy relay awaiting a dial)"
        if data:
            return "banner", f"unexpected banner: {data[:60]!r}"
        return "eof", ("relay accepted then immediately closed — its "
                       "upstream/backend is gone; NO client-side action "
                       "can recover this, wait for the remote end")
    finally:
        s.close()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe-timeout", type=float, default=75.0)
    args = ap.parse_args(argv)

    print("== 1. leaked local clients holding the relay ==")
    leaks, scan_note = leaked_clients()
    if scan_note:
        print(f"  {scan_note} — continuing with the remaining checks")
    elif leaks:
        for l in leaks:
            print("  LEAK:", l)
        print("  -> kill the owning pid(s), then re-run; this is the only "
              "locally-fixable wedge cause")
    else:
        print("  none (the single-client slot is not held from this box)")

    print("== 2. relay TCP fingerprint ==")
    kind, detail = relay_fingerprint()
    print(f"  {kind}: {detail}")

    print("== 3. subprocess health probe ==")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from apex_tpu.utils.platform import probe_ambient_backend
    r = probe_ambient_backend(args.probe_timeout)
    print(f"  {'HEALTHY' if r else 'WEDGED'}: {r.detail}")

    print("== 4. watcher ==")
    w = subprocess.run(["pgrep", "-f", "tpu_watch[.]sh"],
                       capture_output=True, text=True)
    pids = (w.stdout or "").split()
    print(f"  tpu_watch.sh: {'running pid ' + ','.join(pids) if pids else 'NOT running'}")
    log = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tpu_watch.out")
    if os.path.exists(log):
        with open(log) as f:
            tail = f.readlines()[-3:]
        for line in tail:
            print("   ", line.rstrip())

    if bool(r):
        print("VERDICT: healthy — one client at a time; stop the watcher "
              "before taking the chip interactively")
        return 0
    print("VERDICT: wedged — "
          + ("kill the leaked client above and re-run"
             if leaks else "no local cause; the watcher owns recovery"))
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
