#!/usr/bin/env python
"""Measure the torch-interop bridge's per-step cost (docs/interop.md).

Three configurations over the same ~25M-param tensor list (CPU):

  packed   — TorchFusedOptimizer + FusedAdam(impl='fused'): one threaded
             C++ pack (csrc/host_pack.cpp) -> step_flat -> one unpack;
  per-leaf — TorchFusedOptimizer + FusedAdam(impl='xla'): the fallback
             copy path (per-leaf DLPack import + full param re-read);
  torch    — torch.optim.Adam, the pure-torch baseline the bridge must
             stay comparable to for the hand-off to be worth it.

Reference anchor: the deprecated contrib interop surface
``apex/contrib/optimizers/fused_adam.py:175`` (step(grads=, scale=)).

Run: ``JAX_PLATFORMS=cpu python tools/bench_interop.py [--params 25]``
Prints one JSON line with per-step ms for each configuration.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-only measurement; the ambient sitecustomize force-registers the
# axon TPU tunnel even over JAX_PLATFORMS=cpu, so pin via force_cpu()
# (docs/tpu_tunnel.md fact 3) before any jax op
from apex_tpu.utils.platform import force_cpu

force_cpu()


def make_tensors(torch, n_million):
    """A BERT-base-ish mix: a few big matrices + many small vectors."""
    g = torch.Generator().manual_seed(0)
    import math
    shapes = []
    total = int(n_million * 1e6)
    while sum(math.prod(s) for s in shapes) < total * 0.9:
        shapes += [(1024, 1024), (4096, 1024), (1024,), (1024,)]
    params = [torch.nn.Parameter(torch.randn(*s, generator=g) * 0.02)
              for s in shapes]
    for p in params:
        p.grad = torch.randn(*p.shape, generator=g) * 0.01
    return params


def time_steps(stepfn, n_warm=2, n_time=10):
    for _ in range(n_warm):
        stepfn()
    t0 = time.perf_counter()
    for _ in range(n_time):
        stepfn()
    return (time.perf_counter() - t0) / n_time * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--params", type=float, default=25.0,
                    help="model size in millions of parameters")
    args = ap.parse_args()

    import torch
    from apex_tpu.interop import TorchFusedOptimizer
    from apex_tpu.optimizers import FusedAdam

    out = {"metric": "interop_step_ms", "backend": "cpu"}

    params = make_tensors(torch, args.params)
    out["n_params"] = int(sum(p.numel() for p in params))
    out["n_tensors"] = len(params)

    opt = TorchFusedOptimizer(params, FusedAdam(lr=1e-3, impl="fused"))
    out["packed_ms"] = round(time_steps(lambda: opt.step()), 2)

    params2 = make_tensors(torch, args.params)
    opt2 = TorchFusedOptimizer(params2, FusedAdam(lr=1e-3, impl="xla"))
    out["per_leaf_ms"] = round(time_steps(lambda: opt2.step()), 2)

    params3 = make_tensors(torch, args.params)
    topt = torch.optim.Adam(params3, lr=1e-3)
    out["torch_adam_ms"] = round(time_steps(topt.step), 2)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
