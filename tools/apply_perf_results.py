#!/usr/bin/env python
"""Apply on-chip benchmark results to the framework's tunable defaults.

Reads the TPU bench artifacts (``BENCH_TPU_r5.json`` +
``BENCH_KERNELS_TPU_r5.json`` by default), applies the PERF_NOTES §5
decision rules, and writes ``apex_tpu/tuned_defaults.json`` — the
measured-tuning profile every tunable default consults
(``apex_tpu/utils/tuning.py``).  Prints a markdown results table
(the PERF_NOTES §8 record) to stdout; ``--notes FILE`` appends it there.

Decision rules (each key is only written when its evidence is present
and TPU-backed; absent keys leave the built-in defaults untouched):

  flash_block_q/k       <- flash_autotune.best (the swept fwd winner)
  flash_bwd_block_q/k   <- flash_bwd_autotune.best (the bwd kernels'
                           shared winner; the bwd chain is fully
                           independent — bwd arg > bwd env pin >
                           flash_bwd_block_q/k profile > 128x128
                           built-in — it NEVER falls back to fwd keys)
  flash_bwd_dq_block_q/k
                        <- flash_bwd_autotune.best_dq (per-kernel sweep)
  flash_bwd_dkv_block_q/k
                        <- best_fused when the fuse decision picked the
                           fused kernel (it runs on the dkv grid and
                           reads these keys), else best_dkv — the keys
                           always carry the config the selected strategy
                           was actually measured at
  flash_bwd_fuse        <- best fused-ladder time vs best dq + best dkv
                           split total; False when the fused ladder has
                           no measured row (a failed kernel must not be
                           re-enabled by the runtime byte-cap heuristic)
  flash_bwd_impl        <- the fair grads(q,k,v) A/B rows, both timing
                           the full fwd+bwd exactly as shipped (Pallas
                           forward either way; only the gradient route
                           differs): pallas wins only when
                           pallas_grads_qkv <= xla_grads_qkv; otherwise
                           backward="auto" routes to XLA
  xent_auto_impl        <- xentropy_fwdbwd speedup (pallas vs xla)
  bert_attn_impl        <- attn_seq_sweep: mean fast-vs-default speedup
                           at seq >= 512 (the flagship's regime)
  layer_norm_use_pallas <- layer_norm_fwdbwd speedup > 1
  mlp_use_pallas        <- mlp_fwdbwd speedup > 1
  zero_impl             <- adam_update AND lamb_stage1 speedups > 1
  ddp_collective_scheme <- the bench ``collectives`` A/B leg: fastest
                           measured MEAN-SEMANTICS scheme at the
                           largest payload (int8_blockscale only
                           eligible with its >=3.5x wire ratio intact;
                           adasum changes the reduction rule and is
                           never auto-selected); a non-fp32 winner
                           also pins collective_min_compress_bytes
  ddp_update_sharding   <- the bench ``update_sharding`` A/B leg:
                           "zero1" iff the fastest ELIGIBLE zero1
                           variant is no slower than the off baseline
                           (the 1/N optimizer-state shrink is then
                           free); an int8-allgather variant is only
                           eligible with its metered >=3.5x ratio
                           intact (a drifted variant's timing must not
                           elect zero1 for a config that won't be
                           consumed), and when it wins it also pins
                           ddp_update_allgather_scheme
  overlap_measured_fraction
                        <- the bench one-step profiled capture
                           (``telemetry.timeline`` over the spmd leg's
                           device trace): the measured EXPOSED-comm
                           fraction, consumed by ``parallel.plan``'s
                           comm model as its overlap factor; only
                           persisted when the capture actually
                           measured collective time (comm_ms > 0)
  ddp_overlap           <- the bench ``overlap`` A/B leg (async
                           overlap execution, parallel.overlap):
                           "bucketed" iff the leg proved loss parity
                           AND the bucketed step is no slower than the
                           deferred baseline; the winner's per-leg
                           profiled capture also pins
                           overlap_fraction_<scheme> — the per-scheme
                           exposed-comm fraction overlap-capable dp
                           plans price their wire with
  plan_*                <- the bench ``plan`` A/B leg (auto-parallel
                           planner, parallel.plan): the MEASURED
                           winner's full knob dict (dp/tp/sp + zero /
                           update_sharding / collective scheme),
                           persisted only when the calibration drift
                           guard holds (model error <= 25% and the
                           predicted pick within 25% of the measured
                           winner) and the winner is no slower than
                           the all-defaults baseline

The headline flat-engine winner and vs_baseline are recorded in the
table (informational — the optimizer ``impl`` is a user-facing state
layout choice, not auto-flipped).

Run automatically by tpu_watch.sh after both benches complete; safe to
re-run by hand.  Refuses to write from non-TPU artifacts.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _tuning_schema():
    """The committed profile schema (apex_tpu/utils/tuning.py), loaded
    file-based so the CLI never pays the jax import."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_apex_tpu_tuning",
        os.path.join(REPO, "apex_tpu", "utils", "tuning.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _telemetry_schema():
    """The committed telemetry record schema
    (apex_tpu/telemetry/registry.py), loaded file-based like
    :func:`_tuning_schema` so the CLI never pays the jax import (the
    registry module keeps jax out of module scope for exactly this)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_apex_tpu_telemetry_registry",
        os.path.join(REPO, "apex_tpu", "telemetry", "registry.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _goodput_schema():
    """The committed goodput-ledger schema
    (apex_tpu/telemetry/goodput.py), loaded file-based like
    :func:`_telemetry_schema` so the CLI never pays the jax import
    (the goodput module keeps jax AND its package-relative imports out
    of module scope for exactly this)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_apex_tpu_telemetry_goodput",
        os.path.join(REPO, "apex_tpu", "telemetry", "goodput.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def goodput_violations(artifact) -> list:
    """Audit for every goodput-ledger doc embedded in an artifact
    (ISSUE 15): the ``goodput`` block the bench leg embeds and the
    guard's ``GOODPUT.json`` both carry ``kind: "goodput_ledger"`` —
    each must satisfy the committed ledger schema, whose load-bearing
    checks are that the classes PARTITION the measured wall-clock
    exactly, every fraction sits in [0, 1], and replay badput is
    present iff a rollback/restore was metered.  Warnings only, same
    posture as the other audits."""
    out = []
    schema = None   # loaded once, and only if a ledger doc exists

    def walk(node, path):
        nonlocal schema
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
            return
        if not isinstance(node, dict):
            return
        if node.get("kind") == "goodput_ledger":
            if schema is None:
                schema = _goodput_schema()
            out.extend(f"{path}: {v}"
                       for v in schema.goodput_violations(node))
            return   # a ledger doc has no nested ledgers
        for k, v in node.items():
            if k != "telemetry":
                walk(v, f"{path}.{k}")

    walk(artifact if isinstance(artifact, dict) else {}, "artifact")
    return out


def _serve_schema():
    """The committed serve-ledger schema
    (apex_tpu/telemetry/serve_ledger.py), loaded file-based like
    :func:`_goodput_schema` so the CLI never pays the jax import (the
    serve-ledger module keeps jax out of module scope for exactly
    this)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_apex_tpu_telemetry_serve_ledger",
        os.path.join(REPO, "apex_tpu", "telemetry", "serve_ledger.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def serve_violations(artifact) -> list:
    """Audit for the continuous-batching serving leg (ISSUE 18): every
    embedded serve-ledger doc (``kind: "serve_ledger"`` — the bench
    leg's per-variant ledgers and a scheduler-written ``SERVE.json``
    both carry it) must satisfy the committed ledger schema, whose
    load-bearing checks are that the ledger classes PARTITION every
    request's wall time EXACTLY (integer microseconds, tolerance
    zero), p99 is present when anything was served, shed requests are
    metered in the ``shed`` class, and an int8 O-level carries its
    metered compression ratio.  The leg-level winner must point at a
    measured variant.  Warnings only, same posture as the other
    audits."""
    out = []
    schema = None   # loaded once, and only if a serve doc exists

    def walk(node, path):
        nonlocal schema
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
            return
        if not isinstance(node, dict):
            return
        if node.get("kind") == "serve_ledger":
            if schema is None:
                schema = _serve_schema()
            out.extend(f"{path}: {v}"
                       for v in schema.serve_violations(node))
            return   # a ledger doc has no nested ledgers
        if node.get("leg") == "serve" and "error" not in node:
            variants = node.get("variants")
            if not isinstance(variants, list) or not variants:
                out.append(f"{path}: serve leg carries no variants")
            else:
                for i, v in enumerate(variants):
                    if not isinstance(v.get("ledger"), dict):
                        out.append(f"{path}.variants[{i}]: no embedded "
                                   f"serve ledger")
                    if v.get("p99_ms") is None:
                        out.append(f"{path}.variants[{i}]: p99 missing")
                    if v.get("olevel") == "int8" and not (
                            isinstance(v.get("compression_ratio"),
                                       (int, float))
                            and v["compression_ratio"] > 1.0):
                        out.append(
                            f"{path}.variants[{i}]: int8 variant "
                            f"without a metered compression ratio > 1")
            win = node.get("winner")
            if isinstance(variants, list) and variants:
                keys = {(v.get("olevel"), v.get("decode_width"))
                        for v in variants}
                if not isinstance(win, dict) or (
                        win.get("olevel"),
                        win.get("decode_width")) not in keys:
                    out.append(f"{path}: winner is not a measured "
                               f"variant")
        for k, v in node.items():
            if k != "telemetry":
                walk(v, f"{path}.{k}")

    walk(artifact if isinstance(artifact, dict) else {}, "artifact")
    return out


def telemetry_violations(artifact) -> list:
    """Schema complaints for every ``telemetry`` block embedded in a
    bench artifact (``{"records": [...], "summary": {...}}`` blocks, as
    ``bench.telemetry_summary`` writes them).  A bench leg that embeds
    off-schema records has drifted from the committed contract —
    surfaced as warnings here and asserted empty by test_tuning.py /
    test_bench_legs.py."""
    out = []
    schema = None   # loaded once, and only if a telemetry block exists

    def walk(node, path):
        nonlocal schema
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
            return
        if not isinstance(node, dict):
            return
        tel = node.get("telemetry")
        if isinstance(tel, dict) and isinstance(tel.get("records"), list):
            if schema is None:
                schema = _telemetry_schema()
            out.extend(f"{path}.telemetry: {v}" for v in
                       schema.records_violations(tel["records"]))
        for k, v in node.items():
            if k != "telemetry":
                walk(v, f"{path}.{k}")

    walk(artifact if isinstance(artifact, dict) else {}, "artifact")
    return out


def perf_field_violations(artifact) -> list:
    """Legs that embed a telemetry block but no MFU / peak-HBM evidence
    (VERDICT round-5: 'no MFU/HBM fields landed in the captured legs').
    A leg satisfies the audit with either the leg-dict fields
    (``mfu_pct``/``mfu_analytic_pct``, ``hbm_*_bytes`` — a BYTE count;
    ``hbm_util_pct`` is a utilization ratio and must not stand in for
    the missing footprint) or the equivalent gauges inside its
    telemetry records (``mfu_pct``, ``mem.*`` — the
    ``bench.leg_telemetry`` shape).  Warnings only — the caller gates
    on the artifact being TPU-backed, and legs an assembled mixed
    artifact tags ``_backend != tpu`` (CPU stand-ins honestly carry no
    MFU) are skipped."""
    out = []

    def walk(node, path):
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
            return
        if not isinstance(node, dict):
            return
        tel = node.get("telemetry")
        if isinstance(tel, dict) and node.get("_backend") in (None, "tpu") \
                and node.get("leg") not in ("collectives",
                                            "update_sharding",
                                            "goodput",
                                            "overlap"):
            # the collectives / update_sharding / goodput / overlap
            # legs carry byte+ms / wall-partition / parity evidence,
            # not MFU — their own audits (collective_violations /
            # update_sharding_violations / goodput_violations /
            # overlap_exec_violations) check them instead
            recs = tel.get("records") or []
            gauges = {r.get("name") for r in recs
                      if isinstance(r, dict) and r.get("type") == "gauge"}
            has_hbm = (any(k.startswith("hbm_") and k.endswith("_bytes")
                           and node[k] is not None for k in node)
                       or any(isinstance(n, str) and n.startswith("mem.")
                              for n in gauges))
            has_mfu = (any(k.startswith("mfu") for k in node)
                       or "mfu_pct" in gauges)
            if not has_hbm:
                out.append(f"{path}: leg embeds telemetry but no "
                           "peak-HBM field (hbm_* / mem.* gauge)")
            if not has_mfu:
                out.append(f"{path}: leg embeds telemetry but no MFU "
                           "field (mfu_pct / mfu_analytic_pct)")
        for k, v in node.items():
            if k != "telemetry":
                walk(v, f"{path}.{k}")

    walk(artifact if isinstance(artifact, dict) else {}, "artifact")
    return out


def collective_violations(artifact) -> list:
    """Audit for the bench ``collectives`` A/B leg (ISSUE 7 satellite):
    the leg must embed schema-valid telemetry whose counters carry the
    compressed-bytes evidence, and the int8_blockscale row must show
    the >=3.5x wire reduction the acceptance criterion demands — a leg
    that 'measured' int8 without the byte win has drifted from the
    scheme's wire format.  Warnings only, same posture as the other
    audits."""
    out = []

    def walk(node, path):
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
            return
        if not isinstance(node, dict):
            return
        if node.get("leg") == "collectives" and isinstance(
                node.get("schemes"), dict):
            schemes = node["schemes"]
            if not isinstance(node.get("telemetry"), dict):
                out.append(f"{path}: collectives leg embeds no telemetry")
            else:
                recs = node["telemetry"].get("records") or []
                names = {r.get("name") for r in recs
                         if isinstance(r, dict)}
                if "ddp.allreduce_compressed_bytes" not in names:
                    out.append(f"{path}: collectives telemetry carries "
                               "no ddp.allreduce_compressed_bytes counter")
            int8 = schemes.get("int8_blockscale")
            if not isinstance(int8, dict):
                out.append(f"{path}: collectives leg has no "
                           "int8_blockscale row")
            elif not (isinstance(int8.get("ratio"), (int, float))
                      and int8["ratio"] >= 3.5):
                out.append(f"{path}: int8_blockscale compression ratio "
                           f"{int8.get('ratio')!r} < 3.5")
        for k, v in node.items():
            if k != "telemetry":
                walk(v, f"{path}.{k}")

    walk(artifact if isinstance(artifact, dict) else {}, "artifact")
    return out


def update_sharding_violations(artifact) -> list:
    """Audit for the bench ``update_sharding`` A/B leg (ISSUE 8
    satellite): the leg must embed schema-valid telemetry whose
    counters carry the new ``ddp.reduce_scatter``/``ddp.param_allgather``
    byte evidence plus a peak-HBM gauge, the per-replica optimizer-state
    shrink must actually track the world size (~1/N), and an int8
    allgather row must show the >=3.5x wire win the scheme promises.
    Warnings only, same posture as the other audits."""
    out = []

    def walk(node, path):
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
            return
        if not isinstance(node, dict):
            return
        if node.get("leg") == "update_sharding" and isinstance(
                node.get("modes"), dict):
            tel = node.get("telemetry")
            if not isinstance(tel, dict):
                out.append(f"{path}: update_sharding leg embeds no "
                           "telemetry")
            else:
                recs = tel.get("records") or []
                names = {r.get("name") for r in recs
                         if isinstance(r, dict)}
                for need in ("ddp.reduce_scatter_bytes",
                             "ddp.param_allgather_bytes",
                             "ddp.opt_state_bytes_per_replica"):
                    if need not in names:
                        out.append(f"{path}: update_sharding telemetry "
                                   f"carries no {need}")
                if not any(isinstance(n, str) and n.startswith("mem.")
                           for n in names):
                    out.append(f"{path}: update_sharding telemetry "
                               "carries no peak-HBM (mem.*) gauge")
            world = node.get("world")
            shrink = node.get("opt_state_shrink")
            if isinstance(world, int) and world > 1:
                if not (isinstance(shrink, (int, float))
                        and shrink >= 0.75 * world):
                    out.append(
                        f"{path}: opt_state_shrink {shrink!r} does not "
                        f"track world {world} (~1/N expected)")
            for mode, row in node["modes"].items():
                if "int8" in mode and isinstance(row, dict):
                    ratio = row.get("ag_ratio")
                    if not (isinstance(ratio, (int, float))
                            and ratio >= 3.5):
                        out.append(f"{path}: {mode} allgather ratio "
                                   f"{ratio!r} < 3.5")
        for k, v in node.items():
            if k != "telemetry":
                walk(v, f"{path}.{k}")

    walk(artifact if isinstance(artifact, dict) else {}, "artifact")
    return out


def overlap_violations(artifact) -> list:
    """Audit for the one-step profiled-capture ``overlap`` block
    (ISSUE 13): a leg that embeds one must carry consistent exposed-
    comm evidence — numeric compute/comm/exposed ms, exposed <= comm
    (interval subtraction can never create time), and a fraction in
    [0, 1] that matches exposed/comm.  A block carrying only an
    ``error`` field is an honestly-failed capture and passes (the leg
    keeps its timing numbers).  Warnings only, same posture as the
    other audits."""
    out = []

    def walk(node, path):
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
            return
        if not isinstance(node, dict):
            return
        ov = node.get("overlap")
        if isinstance(ov, dict) and "error" not in ov:
            nums = {k: ov.get(k) for k in ("compute_ms", "comm_ms",
                                           "exposed_comm_ms")}
            bad = [k for k, v in nums.items()
                   if not isinstance(v, (int, float))]
            if bad:
                out.append(f"{path}.overlap: non-numeric {bad}")
            else:
                if ov["exposed_comm_ms"] > ov["comm_ms"] + 1e-6:
                    out.append(f"{path}.overlap: exposed_comm_ms "
                               f"{ov['exposed_comm_ms']} > comm_ms "
                               f"{ov['comm_ms']}")
                frac = ov.get("exposed_comm_fraction")
                if ov["comm_ms"] > 0:
                    if not (isinstance(frac, (int, float))
                            and 0.0 <= frac <= 1.0):
                        out.append(f"{path}.overlap: bad "
                                   f"exposed_comm_fraction {frac!r}")
                    elif abs(frac - ov["exposed_comm_ms"]
                             / ov["comm_ms"]) > 1e-3:
                        out.append(f"{path}.overlap: fraction {frac} "
                                   "inconsistent with exposed/comm")
                elif frac is not None:
                    out.append(f"{path}.overlap: fraction {frac!r} "
                               "claimed with no measured comm")
        for k, v in node.items():
            if k != "telemetry":
                walk(v, f"{path}.{k}")

    walk(artifact if isinstance(artifact, dict) else {}, "artifact")
    return out


def overlap_exec_violations(artifact) -> list:
    """Audit for the bench ``overlap`` A/B leg (PR 16): the leg must
    carry both modes (deferred ``off`` + ``bucketed``) with numeric
    step times, the parity evidence must HOLD (bucketing re-chunks the
    wire; it must never change the numbers — bitwise for the fp32
    scheme), the metered LOGICAL allreduce bytes must match across
    modes, and when both legs embed a profiled capture with measured
    collective time, the bucketed ``exposed_comm_fraction`` must not
    exceed the deferred one — an overlap execution that exposes MORE
    wire than the deferred path is a regression, not a winner.
    Warnings only, same posture as the other audits."""
    out = []

    def walk(node, path):
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
            return
        if not isinstance(node, dict):
            return
        if node.get("leg") == "overlap" and isinstance(
                node.get("modes"), dict):
            modes = node["modes"]
            rows = {m: r for m, r in modes.items()
                    if isinstance(r, dict)
                    and isinstance(r.get("step_ms"), (int, float))}
            for need in ("off", "bucketed"):
                if need not in rows:
                    out.append(f"{path}: overlap leg carries no "
                               f"measured {need!r} mode")
            if "off" in rows and "bucketed" in rows:
                if node.get("parity_ok") is not True:
                    out.append(
                        f"{path}: overlap leg parity not held "
                        f"(parity_ok={node.get('parity_ok')!r}, "
                        f"loss_abs_diff={node.get('loss_abs_diff')!r})")
                if node.get("logical_bytes_equal") is not True:
                    out.append(
                        f"{path}: overlap leg metered LOGICAL bytes "
                        "differ between modes (bucketing changed what "
                        "is reduced)")
                fracs = {}
                for m, r in rows.items():
                    ov = r.get("overlap")
                    if isinstance(ov, dict) and "error" not in ov \
                            and isinstance(ov.get("comm_ms"),
                                           (int, float)) \
                            and ov["comm_ms"] > 0 \
                            and isinstance(
                                ov.get("exposed_comm_fraction"),
                                (int, float)):
                        fracs[m] = ov["exposed_comm_fraction"]
                if "off" in fracs and "bucketed" in fracs \
                        and fracs["bucketed"] > fracs["off"] + 1e-6:
                    out.append(
                        f"{path}: bucketed exposed_comm_fraction "
                        f"{fracs['bucketed']} exceeds deferred "
                        f"{fracs['off']}")
        for k, v in node.items():
            if k != "telemetry":
                walk(v, f"{path}.{k}")

    walk(artifact if isinstance(artifact, dict) else {}, "artifact")
    return out


def plan_violations(artifact) -> list:
    """Audit for the bench ``plan`` A/B leg (ISSUE 10): the leg must
    carry measured rows (>= 2, including the all-defaults baseline)
    with predictions attached, and the CALIBRATION DRIFT GUARD must
    hold — the measured winner's step time within 25% of the plan the
    model ranked first (its first measurable candidate), and the
    model's own calibration error under 25%.  A drifted artifact means
    the cost model no longer describes this machine; its persisted
    ``plan_*`` winners can't be trusted.  Warnings only, same posture
    as the other audits."""
    out = []

    def walk(node, path):
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
            return
        if not isinstance(node, dict):
            return
        if node.get("leg") == "plan" and "plans" in node:
            rows = [r for r in (node.get("plans") or [])
                    if isinstance(r, dict)
                    and isinstance(r.get("measured_ms"), (int, float))]
            if len(rows) < 2:
                out.append(f"{path}: plan leg measured {len(rows)} "
                           "plans (need the ranked pick AND the "
                           "baseline)")
            else:
                # drift = the ranked pick losing to a SAME-FAMILY row
                # by >25%: within a family the calibration is one-point
                # so a mis-ranking is the model's fault.  Cross-family
                # gaps carry each engine's systematic stack offset
                # (ISSUE 12 — e.g. the GSPMD tp step swaps interpret-
                # mode Pallas kernels for XLA paths on CPU) and are
                # audited via family_calibration_error_pct instead.
                # Rows without a family key (pre-ISSUE-12 artifacts)
                # all read None and keep the old whole-table check.
                top_ms = rows[0]["measured_ms"]
                fam0 = rows[0].get("family")
                best_ms = min(r["measured_ms"] for r in rows
                              if r.get("family") == fam0)
                if best_ms and top_ms > 1.25 * best_ms:
                    out.append(
                        f"{path}: calibration drift — predicted pick "
                        f"measured {top_ms} ms vs measured winner "
                        f"{best_ms} ms (>25% apart)")
            err = node.get("calibration_error_pct")
            if not isinstance(err, (int, float)):
                out.append(f"{path}: plan leg carries no "
                           "calibration_error_pct")
            elif err > 25.0:
                out.append(f"{path}: calibration error {err}% > 25%")
            # ISSUE 12: tp/sp/zero winners must be MEASUREMENT-backed —
            # a winner field claiming an engine family with no measured
            # row carrying those exact knobs is a prediction-only
            # winner, which decide() must never persist
            win = node.get("measured_winner")
            if isinstance(win, dict) and (
                    win.get("tp", 1) > 1 or win.get("sp", 1) > 1
                    or win.get("pp_stages", 1) > 1
                    or win.get("ep", 1) > 1 or win.get("zero")):
                if not any(r.get("knobs") == win for r in rows):
                    out.append(
                        f"{path}: measured_winner engages "
                        "tp/sp/pp/ep/zero but no measured row carries "
                        "those knobs — prediction-only winner")
            # the per-family one-point calibration must hold for the
            # model-parallel families the engine measured (anchors read
            # 0 by construction; non-anchor rows are the real check)
            for r in rows:
                ferr = r.get("family_calibration_error_pct")
                if r.get("family") in ("tp", "sp", "pp", "ep") and \
                        isinstance(ferr, (int, float)) and ferr > 25.0:
                    out.append(
                        f"{path}: {r.get('plan')} family calibration "
                        f"error {ferr}% > 25%")
            if not isinstance(node.get("telemetry"), dict):
                out.append(f"{path}: plan leg embeds no telemetry")
        for k, v in node.items():
            if k != "telemetry":
                walk(v, f"{path}.{k}")

    walk(artifact if isinstance(artifact, dict) else {}, "artifact")
    return out


def _cfg(best):
    """Strictly-validated ``"QxK"`` config string -> (q, k) ints, else
    None.  A non-config winner (``jax_ref_fwdbwd`` has a single 'x' in
    'jax') must SKIP the key, not crash decide() with a ValueError from
    int() — ADVICE r5 #3."""
    if isinstance(best, str) and re.fullmatch(r"\d+x\d+", best):
        return tuple(int(v) for v in best.split("x"))
    return None


def _load(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        print(f"[apply_perf] cannot read {path}: {e}", file=sys.stderr)
        return None


def _tpu_kernel(kernels, name):
    """Kernel record, only if TPU-backed (handles the mixed-backend
    ``_backend`` tagging of assembled partials)."""
    rec = (kernels or {}).get(name)
    if not isinstance(rec, dict):
        return None
    if rec.get("_backend") not in (None, "tpu"):
        return None
    return rec


def decide(bench, kern):
    """(profile dict, list of (knob, decision, evidence) table rows)."""
    prof = {}
    rows = []

    kernels = (kern or {}).get("kernels") if isinstance(kern, dict) else None
    kern_tpu = isinstance(kern, dict) and kern.get("backend") in ("tpu",
                                                                  "mixed")

    if kern_tpu:
        at = _tpu_kernel(kernels, "flash_autotune")
        qk = _cfg(at.get("best")) if at else None
        if qk:
            prof["flash_block_q"], prof["flash_block_k"] = qk
            rows.append(("flash blocks", f"{qk[0]}x{qk[1]}",
                         f"autotune sweep {at.get('sweep_ms')}"))

        bt = _tpu_kernel(kernels, "flash_bwd_autotune")
        if bt:
            sweep = bt.get("sweep_ms") or {}
            qk = _cfg(bt.get("best"))
            if qk:
                prof["flash_bwd_block_q"], prof["flash_bwd_block_k"] = qk
                rows.append(("flash bwd blocks", f"{qk[0]}x{qk[1]}",
                             "best split total over the shared ladder"))
            def _ms(prefix):
                vals = [t for c, t in sweep.items()
                        if c.startswith(prefix)
                        and isinstance(t, (int, float))]
                return min(vals) if vals else None

            fused, dq_ms, dkv_ms = _ms("fused_"), _ms("dq_"), _ms("dkv_")
            fuse = None
            if None not in (dq_ms, dkv_ms):
                # fused must have a MEASURED win; a fused ladder that
                # failed outright (fused is None) records False so the
                # runtime byte-cap heuristic cannot re-enable a kernel
                # that just failed on this chip
                fuse = fused is not None and fused < dq_ms + dkv_ms
                prof["flash_bwd_fuse"] = fuse
                rows.append(("flash_bwd_fuse", str(fuse).lower(),
                             f"fused {fused} ms vs split "
                             f"{round(dq_ms + dkv_ms, 3)} ms"
                             if fused is not None else
                             f"no fused row measured; split "
                             f"{round(dq_ms + dkv_ms, 3)} ms"))
            elif fused is not None:
                # the split total is unmeasurable (a dq or dkv ladder
                # with no surviving row) while the fused ladder DID
                # measure: fused is the only strategy with on-chip
                # evidence, so pin it on.  Leaving flash_bwd_fuse
                # unwritten here would let the runtime byte-cap
                # heuristic pick the fused kernel while the dkv keys
                # below carried best_dkv — split-measured blocks the
                # fused kernel never ran at (ROADMAP deferral a).
                fuse = True
                prof["flash_bwd_fuse"] = True
                rows.append(("flash_bwd_fuse", "true",
                             f"fused {fused} ms; split total unmeasured "
                             f"(dq {dq_ms} ms, dkv {dkv_ms} ms) — only "
                             f"measured strategy"))

            qk = _cfg(bt.get("best_dq"))
            if qk:
                prof["flash_bwd_dq_block_q"] = qk[0]
                prof["flash_bwd_dq_block_k"] = qk[1]
                rows.append(("flash bwd dq blocks", f"{qk[0]}x{qk[1]}",
                             "per-kernel sweep best_dq"))
            # the dkv profile keys feed BOTH the split dkv kernel and the
            # fused kernel (it runs on the dkv grid — _clamp_blocks'
            # "fused" chain reads the dkv keys), so they must carry the
            # config the selected strategy actually measured: best_fused
            # when fuse wins, best_dkv otherwise.  Writing best_dkv with
            # fuse=true would ship a fused config that was never timed.
            kv_name = "best_fused" if fuse else "best_dkv"
            qk = _cfg(bt.get(kv_name))
            if qk:
                prof["flash_bwd_dkv_block_q"] = qk[0]
                prof["flash_bwd_dkv_block_k"] = qk[1]
                rows.append(("flash bwd dkv blocks", f"{qk[0]}x{qk[1]}",
                             f"per-kernel sweep {kv_name} (the strategy "
                             f"the fuse decision selected)"))

            p_ab = sweep.get("pallas_grads_qkv")
            x_ab = sweep.get("xla_grads_qkv")
            if isinstance(p_ab, (int, float)) \
                    and isinstance(x_ab, (int, float)):
                # the auto-fallback rule: the Pallas backward must WIN the
                # fair grads(q,k,v) A/B or backward="auto" ships the
                # measured XLA pair instead of a regression
                prof["flash_bwd_impl"] = ("pallas" if p_ab <= x_ab
                                          else "xla")
                rows.append(("flash_bwd_impl", prof["flash_bwd_impl"],
                             f"grads(q,k,v) A/B: pallas {p_ab} ms vs "
                             f"xla {x_ab} ms"))

        xe = _tpu_kernel(kernels, "xentropy_fwdbwd") or _tpu_kernel(
            kernels, "xentropy_fwd")
        sp = xe.get("speedup") if xe else None
        if isinstance(sp, (int, float)):
            prof["xent_auto_impl"] = "pallas" if sp > 1.0 else "xla"
            rows.append(("xent_auto_impl", prof["xent_auto_impl"],
                         f"pallas speedup {sp}x"))

        sweep = _tpu_kernel(kernels, "attn_seq_sweep")
        by_seq = (sweep or {}).get("by_seq") or {}
        longs = [r.get("speedup") for s, r in by_seq.items()
                 if isinstance(r, dict) and int(s) >= 512
                 and isinstance(r.get("speedup"), (int, float))]
        if longs:
            mean_sp = sum(longs) / len(longs)
            prof["bert_attn_impl"] = "fast" if mean_sp >= 1.0 else "default"
            rows.append(("bert_attn_impl", prof["bert_attn_impl"],
                         f"mean fast-vs-default speedup {mean_sp:.2f}x "
                         f"at seq>=512 (n={len(longs)})"))

        ln = _tpu_kernel(kernels, "layer_norm_fwdbwd")
        sp = ln.get("speedup") if ln else None
        if isinstance(sp, (int, float)):
            prof["layer_norm_use_pallas"] = sp > 1.0
            rows.append(("layer_norm_use_pallas",
                         str(prof["layer_norm_use_pallas"]).lower(),
                         f"pallas speedup {sp}x"))

        ml = _tpu_kernel(kernels, "mlp_fwdbwd")
        sp = ml.get("speedup") if ml else None
        if isinstance(sp, (int, float)):
            prof["mlp_use_pallas"] = sp > 1.0
            rows.append(("mlp_use_pallas",
                         str(prof["mlp_use_pallas"]).lower(),
                         f"pallas speedup {sp}x"))

        zs = []
        for name in ("adam_update", "lamb_stage1"):
            k = _tpu_kernel(kernels, name)
            sp = k.get("speedup") if k else None
            if isinstance(sp, (int, float)):
                zs.append(sp)
        if len(zs) == 2:
            prof["zero_impl"] = "fused" if min(zs) > 1.0 else "xla"
            rows.append(("zero_impl", prof["zero_impl"],
                         f"pallas speedups adam {zs[0]}x / lamb-s1 {zs[1]}x"))

    if isinstance(bench, dict) and bench.get("backend") in ("tpu", "mixed"):
        det = bench.get("detail") or {}
        if det.get("_backend") in (None, "tpu"):
            winner = det.get("winner")
            if winner:
                rows.append(("headline winner (informational)", winner,
                             f"xla {det.get('xla_impl_ms')} ms vs "
                             f"fused_flat {det.get('fused_flat_impl_ms')} ms; "
                             f"optax {det.get('optax_baseline_ms')} ms; "
                             f"vs_baseline {bench.get('vs_baseline')}"))
        coll = det.get("collectives")
        if isinstance(coll, dict) \
                and coll.get("_backend") in (None, "tpu") \
                and isinstance(coll.get("schemes"), dict):
            # ddp_collective_scheme <- fastest measured scheme at the
            # largest payload, among the MEAN-SEMANTICS schemes only:
            # adasum is a different reduction rule (self-scaling;
            # gradient_average stops applying), so a host-ms win must
            # never auto-change training semantics — it stays explicit
            # opt-in.  int8 is only eligible when its measured wire
            # ratio actually delivers the >=3.5x the convergence proof
            # (tests/L0/test_collectives.py A/B) was run at — otherwise
            # the leg drifted from the committed wire format
            cand = {}
            for name, row in coll["schemes"].items():
                if name == "adasum":
                    continue
                ms = row.get("host_ms") if isinstance(row, dict) else None
                if not isinstance(ms, (int, float)):
                    continue
                if name == "int8_blockscale" and not (
                        isinstance(row.get("ratio"), (int, float))
                        and row["ratio"] >= 3.5):
                    continue
                cand[name] = ms
            if cand:
                best = min(cand, key=cand.get)
                prof["ddp_collective_scheme"] = best
                if best != "fp32":
                    # collectives.DEFAULT_MIN_BYTES (kept literal: this
                    # CLI never imports jax); small/precision-critical
                    # leaves stay fp32 under the measured scheme
                    prof["collective_min_compress_bytes"] = 4096
                rows.append(("ddp_collective_scheme", best,
                             "collectives A/B host ms: " + ", ".join(
                                 f"{k} {v}" for k, v in
                                 sorted(cand.items()))))

        us = det.get("update_sharding")
        if isinstance(us, dict) and us.get("_backend") in (None, "tpu") \
                and isinstance(us.get("modes"), dict):
            # ddp_update_sharding <- zero1 iff the fastest measured
            # zero1 variant is no slower than the off baseline (the
            # memory win is free then; a slower step stays opt-in).
            # The winning variant's allgather scheme rides along ONLY
            # with its metered >=3.5x ratio intact — otherwise the leg
            # drifted from the committed wire format.
            modes = us["modes"]
            off_ms = (modes.get("off") or {}).get("step_ms")
            # eligibility mirrors the ddp_collective_scheme rule: an
            # int8-allgather variant whose metered ratio drifted below
            # 3.5x would never have its scheme consumed, so its (faster)
            # timing must not elect zero1 on the fp32 variant's behalf —
            # filter ineligible variants out of the candidate set FIRST
            zrows = {}
            for m, r in modes.items():
                if not (m.startswith("zero1") and isinstance(r, dict)
                        and isinstance(r.get("step_ms"), (int, float))):
                    continue
                if "int8" in m and not (
                        isinstance(r.get("ag_ratio"), (int, float))
                        and r["ag_ratio"] >= 3.5):
                    continue
                zrows[m] = r
            if isinstance(off_ms, (int, float)) and zrows:
                best_z = min(zrows, key=lambda m: zrows[m]["step_ms"])
                win = zrows[best_z]["step_ms"] <= off_ms
                prof["ddp_update_sharding"] = "zero1" if win else "off"
                rows.append((
                    "ddp_update_sharding", prof["ddp_update_sharding"],
                    f"A/B step ms: off {off_ms}, " + ", ".join(
                        f"{m} {r['step_ms']}"
                        for m, r in sorted(zrows.items()))
                    + f"; opt-state shrink {us.get('opt_state_shrink')}x"))
                if win and "int8" in best_z:
                    prof["ddp_update_allgather_scheme"] = \
                        "int8_blockscale"
                    rows.append((
                        "ddp_update_allgather_scheme",
                        "int8_blockscale",
                        f"winning variant's metered allgather "
                        f"ratio {zrows[best_z]['ag_ratio']}x"))

        spmd_leg = det.get("spmd")
        if isinstance(spmd_leg, dict) \
                and spmd_leg.get("_backend") in (None, "tpu") \
                and isinstance(spmd_leg.get("overlap"), dict):
            # overlap_measured_fraction <- the one-step profiled
            # capture's exposed-comm fraction.  Only with measured
            # collective time behind it (comm_ms > 0) and a clean
            # audit — a fraction from a comm-free or inconsistent
            # capture says nothing the planner should consume.
            ov = spmd_leg["overlap"]
            frac = ov.get("exposed_comm_fraction")
            if "error" not in ov \
                    and isinstance(frac, (int, float)) \
                    and not isinstance(frac, bool) \
                    and 0.0 <= frac <= 1.0 \
                    and isinstance(ov.get("comm_ms"), (int, float)) \
                    and ov["comm_ms"] > 0 \
                    and not overlap_violations({"overlap": ov}):
                prof["overlap_measured_fraction"] = round(float(frac), 4)
                rows.append((
                    "overlap_measured_fraction",
                    f"{prof['overlap_measured_fraction']}",
                    f"one-step profiled capture: exposed "
                    f"{ov.get('exposed_comm_ms')} ms of "
                    f"{ov.get('comm_ms')} ms collective time over "
                    f"{ov.get('devices')} devices"))

        ov_leg = det.get("overlap")
        if isinstance(ov_leg, dict) \
                and ov_leg.get("_backend") in (None, "tpu") \
                and isinstance(ov_leg.get("modes"), dict) \
                and not overlap_exec_violations({"overlap": ov_leg}):
            # ddp_overlap <- "bucketed" iff the A/B proved parity AND
            # the bucketed step is no slower than deferred.  The audit
            # above already enforced parity + logical-byte equality +
            # fraction ordering; here only the election remains.
            modes = ov_leg["modes"]
            off_r = modes.get("off") or {}
            buck_r = modes.get("bucketed") or {}
            off_ms = off_r.get("step_ms")
            buck_ms = buck_r.get("step_ms")
            if isinstance(off_ms, (int, float)) \
                    and isinstance(buck_ms, (int, float)):
                win = buck_ms <= off_ms
                prof["ddp_overlap"] = "bucketed" if win else "off"
                rows.append((
                    "ddp_overlap", prof["ddp_overlap"],
                    f"A/B step ms: off {off_ms}, bucketed {buck_ms}; "
                    f"parity_ok {ov_leg.get('parity_ok')} "
                    f"(loss_abs_diff {ov_leg.get('loss_abs_diff')})"))
                # overlap_fraction_<scheme> <- the WINNER's profiled
                # exposed-comm fraction, keyed by the scheme the A/B
                # ran under (how much wire hides depends on how many
                # bytes are on it) — same comm_ms > 0 gate as the
                # global overlap_measured_fraction
                scheme = ov_leg.get("scheme")
                wov = (buck_r if win else off_r).get("overlap")
                if scheme in ("fp32", "bf16", "int8_blockscale") \
                        and isinstance(wov, dict) \
                        and "error" not in wov \
                        and isinstance(wov.get("comm_ms"),
                                       (int, float)) \
                        and wov["comm_ms"] > 0 \
                        and isinstance(
                            wov.get("exposed_comm_fraction"),
                            (int, float)):
                    key = f"overlap_fraction_{scheme}"
                    prof[key] = round(
                        float(wov["exposed_comm_fraction"]), 4)
                    rows.append((
                        key, f"{prof[key]}",
                        f"{prof['ddp_overlap']} leg's one-step "
                        f"profiled capture: exposed "
                        f"{wov.get('exposed_comm_ms')} ms of "
                        f"{wov.get('comm_ms')} ms collective time"))

        pl = det.get("plan")
        if isinstance(pl, dict) and pl.get("_backend") in (None, "tpu") \
                and isinstance(pl.get("plans"), list):
            # plan_* <- the bench ``plan`` leg's MEASURED winner (the
            # model only nominates candidates; measurement elects).
            # Only persisted when the drift guard holds — a winner
            # picked while the cost model was >25% wrong about this
            # machine is evidence of drift, not of a winner — and only
            # when the winner is no slower than the all-defaults
            # baseline (otherwise the defaults ARE the winner).
            mrows = [r for r in pl["plans"] if isinstance(r, dict)
                     and isinstance(r.get("measured_ms"), (int, float))
                     and isinstance(r.get("knobs"), dict)]
            base_ms = pl.get("baseline_step_ms")
            err = pl.get("calibration_error_pct")
            if mrows and isinstance(base_ms, (int, float)) \
                    and isinstance(err, (int, float)) and err <= 25.0 \
                    and not plan_violations({"plan": pl}):
                win = min(mrows, key=lambda r: r["measured_ms"])
                kn = win["knobs"]
                # ISSUE 12 gate: a tp>1 / sp>1 / zero winner may only
                # persist with a MEASURED row behind it.  ``win`` comes
                # from mrows so this holds by construction — the assert
                # keeps a future refactor (e.g. electing the predicted
                # ranking) from silently shipping prediction-only
                # engine-family winners.
                assert any(r["knobs"] == kn for r in mrows)
                if win["measured_ms"] <= base_ms:
                    prof["plan_dp"] = int(kn.get("dp", 1))
                    prof["plan_tp"] = int(kn.get("tp", 1))
                    prof["plan_sp"] = int(kn.get("sp", 1))
                    prof["plan_sp_strategy"] = kn.get("sp_strategy",
                                                      "none")
                    prof["plan_pp_stages"] = int(kn.get("pp_stages", 1))
                    prof["plan_pp_microbatches"] = int(
                        kn.get("pp_microbatches", 1))
                    prof["plan_ep"] = int(kn.get("ep", 1))
                    prof["plan_zero"] = bool(kn.get("zero", False))
                    prof["plan_update_sharding"] = kn.get(
                        "update_sharding", "off")
                    prof["plan_collective_scheme"] = kn.get(
                        "collective_scheme", "fp32")
                    prof["plan_allgather_scheme"] = kn.get(
                        "allgather_scheme", "fp32")
                    rows.append((
                        "plan_* (auto-parallel)",
                        win.get("plan", "winner"),
                        f"measured {win['measured_ms']} ms vs baseline "
                        f"{base_ms} ms over {len(mrows)} measured of "
                        f"{pl.get('feasible')} feasible plans; "
                        f"calibration error {err}%"))

        sv = det.get("serve")
        if isinstance(sv, dict) and sv.get("_backend") in (None, "tpu") \
                and isinstance(sv.get("variants"), list) \
                and isinstance(sv.get("winner"), dict) \
                and "error" not in sv \
                and not serve_violations({"serve": sv}):
            # serve_decode_batch / serve_olevel <- the serving A/B's
            # measured tokens/sec winner, but only from a clean audit
            # (every variant's per-request ledger partitioned exactly,
            # p99 present, int8 compression metered) and only when the
            # winner actually served its load without shedding — a
            # variant that won by shedding work isn't a winner
            win = sv["winner"]
            wrow = next((v for v in sv["variants"]
                         if v.get("olevel") == win.get("olevel")
                         and v.get("decode_width")
                         == win.get("decode_width")), None)
            if wrow and isinstance(wrow.get("tokens_per_sec"),
                                   (int, float)) \
                    and wrow["tokens_per_sec"] > 0 \
                    and not wrow.get("shed"):
                prof["serve_decode_batch"] = int(wrow["decode_width"])
                prof["serve_olevel"] = str(wrow["olevel"])
                rows.append((
                    "serve_decode_batch / serve_olevel",
                    f"{prof['serve_decode_batch']} / "
                    f"{prof['serve_olevel']}",
                    f"serving A/B over {len(sv['variants'])} variants: "
                    f"winner {wrow['tokens_per_sec']} tok/s, p99 "
                    f"{wrow.get('p99_ms')} ms, served "
                    f"{wrow.get('served')} shed {wrow.get('shed')}"))

    return prof, rows


def render(rows):
    out = ["| knob | decision | evidence |", "|---|---|---|"]
    out += [f"| {k} | {d} | {e} |" for k, d, e in rows]
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--bench", default=os.path.join(REPO, "BENCH_TPU_r5.json"))
    ap.add_argument("--kernels",
                    default=os.path.join(REPO, "BENCH_KERNELS_TPU_r5.json"))
    ap.add_argument("--out", default=os.path.join(
        REPO, "apex_tpu", "tuned_defaults.json"))
    ap.add_argument("--notes", help="append the results table to this file")
    ap.add_argument("--dry-run", action="store_true")
    args = ap.parse_args(argv)

    bench = _load(args.bench)
    kern = _load(args.kernels)
    tpu_sourced = any(isinstance(d, dict) and d.get("backend") in
                      ("tpu", "mixed") for d in (bench, kern))
    if not tpu_sourced:
        print("[apply_perf] no TPU-backed artifact found; refusing to write "
              "a tuning profile from CPU numbers", file=sys.stderr)
        return 1

    # telemetry blocks don't feed tuning decisions, but drifted records
    # must not pass silently through the one tool that audits artifacts
    for label, art in (("bench", bench), ("kernels", kern)):
        for v in telemetry_violations(art):
            print(f"[apply_perf] WARNING {label} {v}", file=sys.stderr)
        # TPU-backed legs must carry their MFU/peak-HBM evidence (CPU
        # stand-ins honestly carry no MFU, so they are not audited)
        if isinstance(art, dict) and art.get("backend") in ("tpu", "mixed"):
            for v in perf_field_violations(art):
                print(f"[apply_perf] WARNING {label} {v}", file=sys.stderr)
            # the collectives A/B leg has its own evidence contract
            # (compressed-bytes counters + the >=3.5x int8 ratio)
            for v in collective_violations(art):
                print(f"[apply_perf] WARNING {label} {v}", file=sys.stderr)
            # so does the update_sharding A/B leg (reduce-scatter /
            # param-allgather counters + the ~1/N state shrink)
            for v in update_sharding_violations(art):
                print(f"[apply_perf] WARNING {label} {v}", file=sys.stderr)
            # and the plan A/B leg (measured rows + the >25%
            # calibration drift guard)
            for v in plan_violations(art):
                print(f"[apply_perf] WARNING {label} {v}", file=sys.stderr)
            # and any one-step profiled-capture overlap block (the
            # exposed-comm evidence must be internally consistent)
            for v in overlap_violations(art):
                print(f"[apply_perf] WARNING {label} {v}", file=sys.stderr)
            # and the async-overlap A/B leg (parity must hold and the
            # bucketed leg must not expose MORE wire than deferred)
            for v in overlap_exec_violations(art):
                print(f"[apply_perf] WARNING {label} {v}", file=sys.stderr)
            # and every embedded goodput ledger (classes must partition
            # the wall exactly; replay badput iff rollbacks metered)
            for v in goodput_violations(art):
                print(f"[apply_perf] WARNING {label} {v}", file=sys.stderr)
            # and the serving A/B leg (per-request ledger classes must
            # partition each request's wall exactly; p99 present; int8
            # carries its metered compression ratio)
            for v in serve_violations(art):
                print(f"[apply_perf] WARNING {label} {v}", file=sys.stderr)

    prof, rows = decide(bench, kern)
    table = render(rows)
    print(table)
    if not prof:
        print("[apply_perf] no decidable knobs in the artifacts; nothing "
              "written", file=sys.stderr)
        return 1
    if args.dry_run:
        return 0

    bad = _tuning_schema().schema_violations(prof)
    if bad:
        # the decision engine and the profile consumers have drifted
        # apart; a key the consumers would silently ignore (or choke on)
        # must never reach disk
        print(f"[apply_perf] profile fails the committed schema: "
              f"{'; '.join(bad)}", file=sys.stderr)
        return 1

    prof["_provenance"] = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "bench": os.path.basename(args.bench),
        "kernels": os.path.basename(args.kernels),
    }
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(prof, f, indent=1, sort_keys=True)
    os.replace(tmp, args.out)
    print(f"[apply_perf] wrote {args.out}", file=sys.stderr)

    if args.notes:
        stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
        marker = "\n## 8. Measured winners applied"
        try:
            with open(args.notes) as f:
                content = f.read()
        except OSError:
            content = ""
        # re-runs REPLACE the section (it is always the file's tail)
        # instead of accreting duplicate headings — match the heading
        # number-agnostically so a notes file written when the section
        # was numbered differently (pre-r5: "## 7.") is still replaced
        import re
        m = re.search(r"\n## \d+\. Measured winners applied", content)
        if m:
            content = content[:m.start()]
        with open(args.notes, "w") as f:
            f.write(f"{content}{marker} ({stamp})\n\n"
                    f"{table}\n\nProfile: `apex_tpu/tuned_defaults.json` "
                    f"(every knob consults it — utils/tuning.py).\n")
        print(f"[apply_perf] wrote results table to {args.notes}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
