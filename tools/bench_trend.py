#!/usr/bin/env python
"""Regression watchdog over the committed bench trajectory + goodput
artifacts (ISSUE 15 satellite).

Ingests the per-round bench artifacts (``BENCH_r*.json`` driver
wrappers and ``BENCH_TPU_r*.json`` raw captures), any
``GOODPUT*.json`` run ledgers, and any ``FLEET*.json`` multi-host
merges (``telemetry.fleet``: fleet goodput fraction + max straggler z
become series keyed by host count, so fleet-level drift fails stage 4b
the same way per-leg drift does), assembles per-leg metric series —
step time, throughput, MFU, goodput fraction — keyed by the leg's
config signature (model/batch/seq/layers: a config change starts a NEW
series, it is not a regression), and flags the newest point in each
series when it drifts beyond the tolerance band from the best prior
point.

Backend posture (the repo rule — ``bench.py`` nulls ``vs_baseline``
on CPU for the same reason): **TPU-backed drift fails the run**
(exit 1); CPU/unknown-backend drift is reported as a warning only —
the committed CPU trajectory carries environment noise that says
nothing about the product thesis.  ``--strict-cpu`` promotes CPU
drift to failing.  Schema-invalid goodput ledgers fail regardless of
backend: a ledger whose classes don't partition the wall is broken
accounting, not noise.

One JSON document on stdout with ``--json`` (the ``tpu_watch.sh``
``watch.goodput`` stage's atomic artifact); the human table otherwise.
Exit 0 = no drift, 1 = drift / invalid ledger, 2 = nothing to ingest.

No jax import, ever — this tool runs in CI and in the watcher's probe
loop; the goodput schema is file-loaded exactly like
``apply_perf_results`` loads the telemetry schema.
"""
from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: metric name -> True when LOWER is better
_LOWER_BETTER = {"step_ms": True, "value_ms": True,
                 "images_per_sec": False, "sequences_per_sec": False,
                 "mfu_pct": False, "mfu_analytic_pct": False,
                 "goodput_fraction": False,
                 "fleet_goodput_fraction": False,
                 "fleet_max_straggler_z": True}

_LEG_METRICS = ("step_ms", "images_per_sec", "sequences_per_sec",
                "mfu_pct", "mfu_analytic_pct")

#: leg-config fields that define a series identity: a round that
#: changed the model/shape starts a fresh series
_SIG_FIELDS = ("model", "batch", "seq", "layers", "arch", "chips",
               "global_batch")


def _schema_module(name):
    """File-load a telemetry module for its schema functions (no
    package import, no jax — the apply_perf_results posture)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        f"_apex_tpu_telemetry_{name}",
        os.path.join(REPO, "apex_tpu", "telemetry", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _goodput_schema():
    return _schema_module("goodput")


def _load(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        print(f"[bench_trend] cannot read {path}: {e}", file=sys.stderr)
        return None


def _artifact(doc):
    """Unwrap a driver round file (``{"parsed": {...}}``) to the bench
    artifact; raw artifacts pass through."""
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc if isinstance(doc, dict) else None


def _num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _sig(leg: dict) -> str:
    parts = [f"{k}={leg[k]}" for k in _SIG_FIELDS if k in leg]
    return ",".join(parts) or "-"


def extract_points(artifact: dict, round_name: str):
    """``(series_key, backend, metric, value)`` rows for one artifact.
    The series key folds in the leg name, metric, backend, and the
    leg's config signature, so only like-for-like points compare."""
    rows = []
    backend = artifact.get("backend") or "unknown"
    val = artifact.get("value")
    if _num(val) and val > 0 and artifact.get("unit") == "ms":
        key = f"headline:{artifact.get('metric', 'value')}"
        rows.append((f"{key}|{backend}", backend, "value_ms", float(val)))
    detail = artifact.get("detail")
    if not isinstance(detail, dict):
        return rows

    def leg_rows(name, leg):
        lb = leg.get("_backend") or backend
        sig = _sig(leg)
        for m in _LEG_METRICS:
            if _num(leg.get(m)):
                rows.append((f"{name}:{m}|{lb}|{sig}", lb, m,
                             float(leg[m])))
        gp = leg.get("goodput") if name == "goodput" else None
        if isinstance(gp, dict) and _num(gp.get("goodput_fraction")):
            rows.append((f"goodput:goodput_fraction|{lb}", lb,
                         "goodput_fraction",
                         float(gp["goodput_fraction"])))

    for name, leg in detail.items():
        if isinstance(leg, dict):
            leg_rows(name, leg)
    return rows


def check_series(series: dict, tolerance: float):
    """Drift rows: the NEWEST point in each >=2-point series vs the
    best prior point, beyond the tolerance band."""
    drifts = []
    for key, points in sorted(series.items()):
        if len(points) < 2:
            continue
        metric = points[-1]["metric"]
        lower = _LOWER_BETTER.get(metric, metric.endswith("_ms"))
        prior = [p["value"] for p in points[:-1]]
        best = min(prior) if lower else max(prior)
        last = points[-1]["value"]
        if best <= 0:
            continue
        ratio = last / best
        bad = ratio > 1.0 + tolerance if lower else ratio < 1.0 - tolerance
        if bad:
            drifts.append({
                "series": key, "metric": metric,
                "backend": points[-1]["backend"],
                "best_prior": best, "last": last,
                "last_round": points[-1]["round"],
                "ratio": round(ratio, 4),
                "direction": "lower_better" if lower else "higher_better",
            })
    return drifts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", default=REPO,
                    help="directory holding the round artifacts")
    ap.add_argument("--glob", action="append", default=None,
                    help="round-artifact glob(s); default "
                         "BENCH_r*.json + BENCH_TPU_r*.json")
    ap.add_argument("--goodput-glob", default="GOODPUT*.json",
                    help="goodput run-artifact glob")
    ap.add_argument("--fleet-glob", default="FLEET*.json",
                    help="fleet merge-artifact glob (telemetry.fleet)")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="allowed fractional drift before flagging")
    ap.add_argument("--strict-cpu", action="store_true",
                    help="CPU/unknown-backend drift fails too (default: "
                         "warning only — CPU stand-ins are noise)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable trend document")
    args = ap.parse_args(argv)

    globs = args.glob or ["BENCH_r*.json", "BENCH_TPU_r*.json"]
    paths = sorted(p for g in globs
                   for p in _glob.glob(os.path.join(args.dir, g)))
    series: dict = {}
    rounds = []
    for path in paths:
        art = _artifact(_load(path))
        if art is None:
            continue
        rnd = os.path.basename(path)
        rounds.append(rnd)
        for key, backend, metric, value in extract_points(art, rnd):
            series.setdefault(key, []).append(
                {"round": rnd, "backend": backend, "metric": metric,
                 "value": value})

    # standalone goodput run artifacts: schema-check every ledger and
    # fold the fractions into one series (ordered by ts, then name)
    schema = None
    ledger_violations = []
    gp_paths = sorted(_glob.glob(os.path.join(args.dir,
                                              args.goodput_glob)))
    gp_docs = []
    for path in gp_paths:
        doc = _load(path)
        if not isinstance(doc, dict):
            continue
        if schema is None:
            schema = _goodput_schema()
        bad = schema.goodput_violations(doc)
        name = os.path.basename(path)
        ledger_violations.extend(f"{name}: {v}" for v in bad)
        if not bad and _num(doc.get("goodput_fraction")):
            gp_docs.append((doc.get("ts") or "", name,
                            float(doc["goodput_fraction"])))
    for ts, name, frac in sorted(gp_docs):
        rounds.append(name)
        series.setdefault("goodput:artifact_fraction", []).append(
            {"round": name, "backend": "run", "metric":
             "goodput_fraction", "value": frac})

    # fleet merge artifacts (telemetry.fleet): the host count is the
    # series signature — a 2-host fleet and a 4-host fleet are
    # different configurations, not a regression — and the points are
    # the fleet goodput fraction + the worst straggler z, so a fleet
    # that starts wasting wall-clock or growing a straggler fails the
    # gate like any TPU-backed leg ("run"-backend, the goodput posture)
    fl_paths = [p for p in sorted(_glob.glob(os.path.join(
        args.dir, args.fleet_glob)))
        if not os.path.basename(p).startswith("FLEET_TRACE")]
    fl_docs = []
    fl_schema = _schema_module("fleet") if fl_paths else None
    for path in fl_paths:
        doc = _load(path)
        if not isinstance(doc, dict):
            continue
        name = os.path.basename(path)
        bad = fl_schema.fleet_violations(doc)
        ledger_violations.extend(f"{name}: {v}" for v in bad)
        if bad:
            continue
        fl_docs.append((doc.get("ts") or "", name, doc))
    for ts, name, doc in sorted(fl_docs, key=lambda t: (t[0], t[1])):
        rounds.append(name)
        sig = f"hosts={doc.get('n_hosts')}"
        frac = (doc.get("goodput") or {}).get("goodput_fraction")
        if _num(frac):
            series.setdefault(f"fleet:goodput_fraction|run|{sig}",
                              []).append(
                {"round": name, "backend": "run",
                 "metric": "fleet_goodput_fraction", "value": float(frac)})
        z = (doc.get("stragglers") or {}).get("max_z")
        if _num(z) and z > 0:
            series.setdefault(f"fleet:max_straggler_z|run|{sig}",
                              []).append(
                {"round": name, "backend": "run",
                 "metric": "fleet_max_straggler_z", "value": float(z)})

    drifts = check_series(series, args.tolerance)
    gate = ("tpu", "run") if not args.strict_cpu else None
    regressions = [d for d in drifts
                   if gate is None or d["backend"] in gate]
    warnings = [d for d in drifts if d not in regressions]

    doc = {
        "kind": "bench_trend",
        "version": 1,
        "rounds": rounds,
        "n_series": len(series),
        "tolerance": args.tolerance,
        "series": series,
        "regressions": regressions,
        "warnings": warnings,
        "ledger_violations": ledger_violations,
        "ok": not regressions and not ledger_violations,
    }
    if args.json:
        print(json.dumps(doc))
    else:
        print(f"bench trend: {len(rounds)} round(s), {len(series)} "
              f"series, tolerance {args.tolerance:.0%}")
        for key, points in sorted(series.items()):
            tail = " -> ".join(f"{p['value']:g}" for p in points[-4:])
            print(f"  {key:<56} {tail}")
        for d in regressions:
            print(f"  REGRESSION {d['series']}: best prior "
                  f"{d['best_prior']:g} -> {d['last']:g} "
                  f"({d['ratio']}x, {d['last_round']})")
        for d in warnings:
            print(f"  warning (non-TPU) {d['series']}: "
                  f"{d['best_prior']:g} -> {d['last']:g} ({d['ratio']}x)")
        for v in ledger_violations:
            print(f"  LEDGER SCHEMA: {v}")
    if not rounds:
        print("[bench_trend] nothing to ingest", file=sys.stderr)
        return 2
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
