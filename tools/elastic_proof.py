#!/usr/bin/env python
"""The elastic kill-N-resume-M proof as a one-shot artifact (ISSUE 11).

Run by ``tpu_watch.sh`` stage 3b: train the flagship-shaped transformer
N-way under TrainGuard with zero1 update-sharding + int8 error-feedback
residuals, kill it mid-epoch with an injected ``resize@K:M`` fault,
resume M-way through ``apex_tpu.elastic`` (manifest world-size detect →
re-plan → canonical-flat reshard), and verify the final params are
BITWISE-identical to a clean M-way run started from the same
checkpoint (independent canonical import, no elastic code).

Prints exactly ONE JSON line on stdout::

    {"metric": "elastic_proof", "backend": "tpu", "from_world": 8,
     "to_world": 4, "ckpt_step": 6, "steps": 12, "bitwise": true,
     "resharded_from": 8, "flat_total_from": 13312,
     "flat_total_to": 12800, "elapsed_s": 31.2}

exit 0 iff the proof holds (bitwise + typed-error gate).  CPU runs the
same logic on the forced 8-device host platform, which is what
``tests/L0/test_elastic.py`` asserts piece-by-piece — this tool exists
to capture the SAME proof on real silicon.
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build(world, cfg, su, global_batch):
    import jax
    import numpy as np  # noqa: F401
    from jax.sharding import PartitionSpec as P
    from apex_tpu.models import transformer_init, transformer_loss
    from apex_tpu.parallel import create_mesh
    from apex_tpu.parallel.mesh import shard_map
    from apex_tpu.utils.pallas import has_vma, _to_varying

    mesh = create_mesh({"data": world}, jax.devices()[:world])
    params0 = transformer_init(jax.random.PRNGKey(0), cfg)
    vma_kw = {} if has_vma() else {"check_vma": False}
    pspec = jax.tree_util.tree_map(lambda _: P(), params0)
    sspec = su.state_pspecs(params0, world)

    def grads_of(params, tokens):
        pv = jax.tree_util.tree_map(
            lambda p: _to_varying(p, ("data",)), params)
        return jax.value_and_grad(lambda p: transformer_loss(
            p, {"tokens": tokens, "targets": tokens}, cfg))(pv)

    @functools.partial(shard_map, mesh=mesh, in_specs=(pspec,),
                       out_specs=(sspec, P("data")))
    def init_s(p):
        return su.init(p), su.init_residual(p)[None]

    def body(params, state, res, tokens):
        loss, grads = grads_of(params, tokens)
        params, state, r2 = su.step(state, grads, params, residual=res[0])
        return params, state, r2[None], jax.lax.pmean(loss, "data")

    jstep = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(pspec, sspec, P("data"), P("data")),
        out_specs=(pspec, sspec, P("data"), P()), **vma_kw))
    state0, res0 = jax.jit(init_s)(params0)

    def step_fn(state, batch):
        params, opt_state, res = state
        params, opt_state, res, loss = jstep(params, opt_state, res,
                                             batch)
        return (params, opt_state, res), loss

    return ((params0, state0, res0), step_fn,
            su.layout_meta(params0, world))


def _import_canonical(template_state, payload, saved_world, layout):
    """Independent canonical-flat import (inline numpy — deliberately
    NOT elastic.reshard_payload, so the proof compares two separate
    implementations of the re-slice)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding
    used, tot = int(layout["used"]), int(layout["flat_total"])
    tmpl_leaves, treedef = jax.tree_util.tree_flatten(template_state)
    out = []
    for t, h in zip(tmpl_leaves, payload["leaves"]):
        h = np.asarray(h)
        if h.shape == tuple(t.shape):
            v = h
        elif h.ndim == 1 and h.shape[0] == tot:
            v = np.zeros((t.shape[0],), h.dtype)
            v[:used] = h[:used]
        elif h.ndim == 2 and h.shape == (saved_world, tot):
            acc = np.zeros((t.shape[1],), h.dtype)
            for row in h:
                r = np.zeros((t.shape[1],), h.dtype)
                r[:used] = row[:used]
                acc = acc + r
            v = np.zeros(tuple(t.shape), h.dtype)
            v[0] = acc
        else:
            raise RuntimeError(f"unexpected leaf {h.shape} vs "
                               f"{tuple(t.shape)}")
        sh = t.sharding if isinstance(t.sharding, NamedSharding) else None
        out.append(jax.device_put(v.astype(t.dtype), sh))
    return jax.tree_util.tree_unflatten(treedef, out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--from-world", type=int, default=None,
                    help="chip count of the killed run (default: all "
                         "visible devices, max 8)")
    ap.add_argument("--to-world", type=int, default=None,
                    help="chip count of the resumed run (default: "
                         "from_world // 2)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--kill-at", type=int, default=6)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--real-data", action="store_true",
                    help="feed a REAL on-disk npz shard set through the "
                         "seekable shard-addressed loader (apex_tpu."
                         "data.sharded) instead of a synthetic "
                         "callable: the kill-N-resume-M proof then "
                         "covers the data plane too — manifest cursor, "
                         "checksum verify, N->M shard re-partition")
    ap.add_argument("--data-dir", default=None,
                    help="existing token-shard dir for --real-data "
                         "(default: a tiny generated set in a temp dir)")
    args = ap.parse_args(argv)

    t0 = time.time()
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    import apex_tpu.elastic as elastic
    from apex_tpu.models import TransformerConfig
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import weight_update as wu
    from apex_tpu.resilience import (CheckpointManager, GuardConfig,
                                     TrainGuard, WorldSizeMismatchError,
                                     faults)

    backend = jax.default_backend()
    n_dev = len(jax.devices())
    from_world = args.from_world or min(8, n_dev)
    to_world = args.to_world or max(1, from_world // 2)
    if from_world > n_dev or to_world > n_dev or from_world == to_world:
        print(json.dumps({"metric": "elastic_proof", "backend": backend,
                          "error": f"need >= 2 devices with distinct "
                                   f"worlds (have {n_dev})"}))
        return 1

    # pos-embed length keeps `used` off the chunk lattice so the two
    # canonical totals actually differ (a real re-chunk, not a no-op)
    cfg = TransformerConfig(vocab_size=64, max_len=20, num_layers=1,
                            d_model=32, num_heads=2, d_ff=64,
                            dtype=jnp.float32)
    # the global batch must shard over BOTH worlds
    global_batch = int(np.lcm(from_world, to_world))

    data_meta = {}
    if args.real_data:
        # a real shard-addressed dataset: non-divisible shard sizes so
        # the (shard, offset) addressing is genuinely exercised, enough
        # records that the kill lands MID-EPOCH (epoch > 0)
        from apex_tpu.data import ShardedLoader, open_dataset
        ddir = args.data_dir
        if ddir is None:
            ddir = tempfile.mkdtemp(prefix="apex_tpu_shards_")
            n0 = 0
            for i, sz in enumerate((global_batch * 2 - 3,
                                    global_batch + 5,
                                    global_batch * 2 - 2)):
                rng = np.random.RandomState(77 + i)
                np.savez(os.path.join(ddir, f"tokens-{i:03d}.npz"),
                         tokens=rng.randint(
                             0, 64, (sz, 20)).astype(np.int32))
                n0 += sz
        dataset = open_dataset(ddir)
        dataset.verify()        # the eager checksum sweep, on record
        make_batch = ShardedLoader(
            dataset, global_batch=global_batch, seed=1,
            num_steps=args.steps,
            transform=lambda b, s: jnp.asarray(b["tokens"]))
        data_meta = {"real_data": True, "data_dir": ddir,
                     "index_digest": dataset.index.digest,
                     "n_records": dataset.n_records,
                     "steps_per_epoch": make_batch.steps_per_epoch}
    else:
        def make_batch(step):
            rng = np.random.RandomState(1000 + step)
            return jnp.asarray(
                rng.randint(0, 64, (global_batch, 20)).astype("int32"))

    def mk_su():
        return wu.ShardedUpdate(
            FusedAdam(lr=1e-2, impl="fused"), axis_name="data",
            collective_scheme="int8_blockscale:min_bytes=0")

    state_n, step_n, layout_n = _build(from_world, cfg, mk_su(),
                                       global_batch)
    state_m, step_m, layout_m = _build(to_world, cfg, mk_su(),
                                       global_batch)

    d = args.ckpt_dir or tempfile.mkdtemp(prefix="apex_tpu_elastic_")

    def gcfg(world, layout):
        return GuardConfig(ckpt_dir=d, save_every_steps=2, check_every=2,
                           backoff_seconds=0.01, enabled=True,
                           world_size=world,
                           ckpt_meta={"plan": {"dp": world},
                                      "layout": layout})

    plan = faults.parse(f"resize@{args.kill_at}:{to_world}")
    _, r1 = TrainGuard(step_n, gcfg(from_world, layout_n),
                       plan=plan).run(state_n, make_batch, args.steps)
    ok_kill = (r1.status == "preempted" and r1.resize_to == to_world)

    # without elastic the mismatch must be the typed, loud error
    try:
        TrainGuard(step_m, gcfg(to_world, layout_m), plan=plan).run(
            state_m, make_batch, args.steps)
        typed_error = False
    except WorldSizeMismatchError:
        typed_error = True

    ck_step, payload, meta = CheckpointManager(d).load_latest(
        with_meta=True)
    state_b = _import_canonical(state_m, payload, from_world,
                                meta["layout"])
    for i in range(ck_step, args.steps):
        state_b, _ = step_m(state_b, make_batch(i))

    er = elastic.ElasticResume()
    state_a, r2 = TrainGuard(step_m, gcfg(to_world, layout_m), plan=plan,
                             elastic=er).run(
        state_m, make_batch, args.steps)

    # real-data gate: the manifest carried the data-plane cursor for
    # THIS dataset, and the elastic resume re-partitioned the shard
    # assignment alongside the optimizer reshard
    data_ok = True
    if args.real_data:
        mdata = meta.get("data") or {}
        data_ok = (mdata.get("index_digest") == data_meta["index_digest"]
                   and isinstance(mdata.get("cursor"), dict)
                   and er.last_data is not None
                   and er.last_data["to_world"] == to_world)

    bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(state_a),
                        jax.tree_util.tree_leaves(state_b)))
    out = {
        "metric": "elastic_proof", "backend": backend,
        "from_world": from_world, "to_world": to_world,
        "ckpt_step": int(ck_step), "steps": args.steps,
        "kill_status": r1.status, "resize_to": r1.resize_to,
        "typed_error_without_elastic": typed_error,
        "resumed_from": r2.resumed_from,
        "resharded_from": r2.resharded_from,
        "flat_total_from": layout_n["flat_total"],
        "flat_total_to": layout_m["flat_total"],
        "bitwise": bool(bitwise),
        "elapsed_s": round(time.time() - t0, 2),
    }
    if args.real_data:
        out.update(data_meta)
        out["data_cursor_ok"] = bool(data_ok)
        out["data_repartition"] = er.last_data
    print(json.dumps(out))
    return 0 if (bitwise and ok_kill and typed_error and data_ok
                 and r2.resharded_from == from_world) else 1


if __name__ == "__main__":
    raise SystemExit(main())
